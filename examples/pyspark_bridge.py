"""PySpark consuming the cobrix_tpu Arrow-IPC bridge.

The reference is used from Spark as
``spark.read.format("cobol").option(...)`` (DefaultSource.scala:36), and
BASELINE.json's north star frames the TPU integration as
``.option("decoder_backend", "tpu")`` on that DataSource. This example
is that shape for cobrix_tpu: each Spark partition asks the bridge
service (cobrix_tpu/bridge.py — run ``python -m cobrix_tpu.bridge`` on
the host with TPU access) for its file shard and receives decoded Arrow
record batches; Spark never touches EBCDIC bytes.

Run (pyspark must be installed on the Spark side; the bridge host needs
only cobrix_tpu):

    python -m cobrix_tpu.bridge --port 8815 &
    spark-submit examples/pyspark_bridge.py \
        --bridge 127.0.0.1:8815 --copybook /path/book.cob data/*.dat
"""
import argparse
import glob
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bridge", default="127.0.0.1:8815")
    ap.add_argument("--copybook", required=True)
    ap.add_argument("files", nargs="+")
    args = ap.parse_args()
    host, port = args.bridge.rsplit(":", 1)
    address = (host, int(port))
    files = sorted(p for pat in args.files for p in glob.glob(pat))
    copybook = open(args.copybook).read()

    try:
        from pyspark.sql import SparkSession
    except ImportError:
        sys.exit("pyspark is not installed; this example runs on a Spark "
                 "driver — see tests/test_bridge.py for the pure-Python "
                 "client exercised in CI")

    spark = SparkSession.builder.appName("cobrix-tpu-bridge").getOrCreate()

    # probe the schema with a row-capped request (the bridge still decodes
    # the probe file on ITS host, but only one row crosses the wire and
    # sits in driver memory), then fan the files out one per task; each
    # task streams its decoded Arrow batches from the bridge (the
    # decoder_backend=tpu shape: decode happens on the bridge host's
    # accelerator, Spark receives columnar batches)
    from pyspark.sql.pandas.types import from_arrow_schema

    from cobrix_tpu.bridge import read_remote

    probe = read_remote(address, files[0], max_records=1,
                        copybook_contents=copybook)
    spark_schema = from_arrow_schema(probe.schema)

    def decode_partition(batches):
        # mapInArrow yields pyarrow.RecordBatch objects of the input rows
        for batch in batches:
            for path in batch.column("path").to_pylist():
                table = read_remote(address, path,
                                    copybook_contents=copybook)
                yield from table.to_batches()

    paths_df = spark.createDataFrame([(f,) for f in files], ["path"]) \
                    .repartition(len(files))
    df = paths_df.mapInArrow(decode_partition, schema=spark_schema) \
                 .persist()  # show + count must not decode every file twice
    df.show(5, truncate=False)
    print(f"rows: {df.count()} from {len(files)} files")
    spark.stop()


if __name__ == "__main__":
    main()
