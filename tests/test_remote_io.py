"""Remote-storage IO subsystem (cobrix_tpu.io): the fsspec byte-range
backend, read-ahead prefetcher, and persistent block + sparse-index
cache, exercised end-to-end through `read_cobol`.

The matrix: fsspec `memory://` and backend-routed local `local://` ×
fixed/VRL framing × sequential/pipelined/multihost execution ×
network-shaped fault injection (ChaosSource). Remote scans must be
byte-identical to local scans of the same bytes; warm re-scans of an
unchanged file must skip both the network (block cache) and the
sequential indexing pass (sparse-index store); a changed file must
invalidate both planes.
"""
import json
import os
import struct
import subprocess
import sys
import tempfile
import uuid

import pytest

fsspec = pytest.importorskip("fsspec")

from cobrix_tpu import prometheus_text, read_cobol
from cobrix_tpu.testing.faults import ChaosSource, register_chaos_backend

from util import hard_timeout

FIXED_COPYBOOK = """
       01  RECORD.
           05  ID        PIC 9(4).
           05  NAME      PIC X(8).
"""
FIXED_RECORD_BYTES = 12

VRL_COPYBOOK = """
       01  RECORD.
           05  ID        PIC 9(4).
           05  PAYLOAD   PIC X(40).
"""
VRL_BODY_BYTES = 44
VRL_RECORD_BYTES = VRL_BODY_BYTES + 4  # + big-endian RDW

VRL_OPTS = dict(is_record_sequence="true", is_rdw_big_endian="true")


def fixed_payload(n: int) -> bytes:
    return b"".join(
        f"{i % 10000:04d}{'N%03d' % (i % 1000):<8}".encode("cp037")
        for i in range(n))


def vrl_payload(n: int) -> bytes:
    out = []
    for i in range(n):
        body = f"{i:04d}{'P%02d' % (i % 90):<40}".encode("cp037")
        out.append(struct.pack(">HH", len(body), 0) + body)
    return b"".join(out)


def mem_write(data: bytes, name: str = "data.dat") -> str:
    """Write `data` into a unique memory:// directory; returns the URL."""
    bucket = f"/t{uuid.uuid4().hex[:12]}"
    fs = fsspec.filesystem("memory")
    with fs.open(f"{bucket}/{name}", "wb") as f:
        f.write(data)
    return f"memory:/{bucket}/{name}"


def local_write(tmp_path, data: bytes, name: str = "data.dat") -> str:
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def io_counters(data) -> dict:
    return data.metrics.as_dict().get("io") or {}


# -- the remote-scan parity matrix ---------------------------------------


@pytest.mark.parametrize("fmt", ["fixed", "vrl"])
@pytest.mark.parametrize("pipeline", [False, True])
def test_memory_scan_matches_local(tmp_path, fmt, pipeline):
    """read_cobol('memory://...') must produce rows and Arrow output
    byte-identical to the local-file scan of the same bytes, with and
    without the chunked pipeline."""
    if fmt == "fixed":
        data, copybook, opts = fixed_payload(4000), FIXED_COPYBOOK, {}
    else:
        data, copybook = vrl_payload(4000), VRL_COPYBOOK
        opts = dict(VRL_OPTS, input_split_size_mb="1")
    if pipeline:
        opts = dict(opts, pipeline_workers="2", chunk_size_mb="1")
    kw = dict(copybook_contents=copybook,
              prefetch_blocks="2", io_block_mb="0.05", **opts)
    remote = read_cobol(mem_write(data), **kw)
    local = read_cobol(local_write(tmp_path, data), **kw)
    assert remote.to_rows() == local.to_rows()
    assert remote.to_arrow().equals(local.to_arrow())
    assert io_counters(remote)["bytes_fetched"] >= len(data)


@pytest.mark.parametrize("fmt", ["fixed", "vrl"])
def test_memory_multihost_scan_matches_local(tmp_path, fmt):
    """The forked multi-process executor over a remote URL: every worker
    opens its own backend connection after the fork and the result is
    identical to the local scan."""
    with hard_timeout(120, f"multihost remote scan ({fmt})"):
        if fmt == "fixed":
            data, copybook, opts = fixed_payload(6000), FIXED_COPYBOOK, {}
        else:
            data, copybook = vrl_payload(30000), VRL_COPYBOOK
            opts = dict(VRL_OPTS, input_split_size_mb="1")
        kw = dict(copybook_contents=copybook, hosts="2",
                  shard_timeout_s="60", scan_deadline_s="100",
                  prefetch_blocks="2", io_block_mb="0.25", **opts)
        remote = read_cobol(mem_write(data), **kw)
        local = read_cobol(local_write(tmp_path, data), **kw)
        assert remote.to_arrow().equals(local.to_arrow())
        # worker-local io counters ship home over the result pipes
        assert io_counters(remote)["bytes_fetched"] >= len(data)


def test_remote_directory_and_glob_scan():
    """A remote *directory* (and glob) lists through the backend with
    the local lister's rules: recursive, hidden files skipped, stable
    order."""
    bucket = f"/t{uuid.uuid4().hex[:12]}"
    fs = fsspec.filesystem("memory")
    a, b = fixed_payload(100), fixed_payload(200)
    for name, payload in (("a.dat", a), ("b.dat", b),
                          (".hidden", b"junk"), ("_meta", b"junk")):
        with fs.open(f"{bucket}/{name}", "wb") as f:
            f.write(payload)
    kw = dict(copybook_contents=FIXED_COPYBOOK)
    table = read_cobol(f"memory:/{bucket}", **kw).to_arrow()
    assert table.num_rows == 300  # hidden files skipped
    glob_table = read_cobol(f"memory:/{bucket}/*.dat", **kw).to_arrow()
    assert glob_table.equals(table)


def test_unknown_scheme_stays_actionable():
    with pytest.raises(ValueError, match="register_stream_backend"):
        read_cobol("noproto77://bucket/x.dat",
                   copybook_contents=FIXED_COPYBOOK)


def test_missing_remote_file_raises_backend_error():
    with pytest.raises(FileNotFoundError):
        read_cobol(f"memory://absent-{uuid.uuid4().hex}/x.dat",
                   copybook_contents=FIXED_COPYBOOK)


# -- fault injection: the retry machinery against network-shaped failures


@pytest.mark.parametrize("fmt", ["fixed", "vrl"])
@pytest.mark.parametrize("pipeline", [False, True])
def test_flaky_backend_retries_are_ledgered(fmt, pipeline):
    """Transient remote failures are retried (backoff) and the retries
    land on the read's diagnostics ledger; rows are complete."""
    scheme = f"flky{uuid.uuid4().hex[:8]}"
    if fmt == "fixed":
        data, copybook, opts = fixed_payload(2000), FIXED_COPYBOOK, {}
        n_expected = 2000
    else:
        data, copybook = vrl_payload(2000), VRL_COPYBOOK
        opts = dict(VRL_OPTS)
        n_expected = 2000
    if pipeline:
        opts = dict(opts, pipeline_workers="2", chunk_size_mb="1")
    source = register_chaos_backend(scheme, data, fail_reads=2)
    out = read_cobol(f"{scheme}://f.dat", copybook_contents=copybook,
                     record_error_policy="permissive",
                     io_retry_attempts="5", io_retry_base_delay_ms="1",
                     prefetch_blocks="2", io_block_mb="0.05", **opts)
    assert len(out.to_rows()) == n_expected
    assert source.failures_served == 2
    assert out.diagnostics.io_retries >= 2


def test_dead_backend_fails_with_its_own_error_type():
    """A backend that never recovers fails promptly and with the
    backend's OWN exception type, not a generic IOError wrap."""
    scheme = f"dead{uuid.uuid4().hex[:8]}"
    register_chaos_backend(scheme, fixed_payload(100), fail_forever=True,
                           error_type=ConnectionResetError)
    with hard_timeout(60, "dead backend"):
        with pytest.raises(ConnectionResetError):
            read_cobol(f"{scheme}://f.dat",
                       copybook_contents=FIXED_COPYBOOK,
                       io_retry_attempts="2", io_retry_base_delay_ms="1",
                       io_retry_deadline_ms="500")


def test_slow_backend_served_through_prefetch():
    """A high-latency filesystem stays correct under read-ahead (the
    pool's fetches overlap the consumer; nothing is lost or reordered)."""
    scheme = f"slow{uuid.uuid4().hex[:8]}"
    data = fixed_payload(3000)
    source = register_chaos_backend(scheme, data, latency_s=0.01)
    out = read_cobol(f"{scheme}://f.dat", copybook_contents=FIXED_COPYBOOK,
                     prefetch_blocks="3", io_block_mb="0.01")
    assert len(out.to_rows()) == 3000
    assert source.slept_s > 0
    io = io_counters(out)
    assert io["prefetch_issued"] > 0
    assert io["prefetch_hits"] + io["prefetch_waits"] > 0


def test_truncating_backend_is_ledgered_not_fatal():
    """Storage EOF short of the advertised size (a truncating proxy /
    torn upload): permissive reads ledger the truncation and return the
    decodable prefix."""
    scheme = f"trnc{uuid.uuid4().hex[:8]}"
    data = fixed_payload(1000)
    cut = 500 * FIXED_RECORD_BYTES + 5  # mid-record
    register_chaos_backend(scheme, data, truncate_at=cut)
    out = read_cobol(f"{scheme}://f.dat", copybook_contents=FIXED_COPYBOOK,
                     record_error_policy="permissive")
    # 500 clean records + the padded partial one, ledgered as truncated
    assert len(out.to_rows()) == 501
    diag = out.diagnostics
    assert diag.corrupt_records == 1
    assert any("truncated" in e.reason for e in diag.entries)


# -- the persistent cache planes -----------------------------------------


def test_warm_vrl_rescan_skips_network_and_index_pass(tmp_path):
    """THE acceptance path: scan a remote VRL file twice with cache_dir
    set. The second scan performs zero sequential index passes
    (sparse-index store hit) and serves blocks from disk (zero backend
    bytes); a changed file invalidates BOTH planes."""
    cache = str(tmp_path / "cache")
    os.makedirs(cache)
    data = vrl_payload(30000)
    url = mem_write(data)
    kw = dict(copybook_contents=VRL_COPYBOOK, cache_dir=cache,
              prefetch_blocks="2", io_block_mb="0.25",
              input_split_size_mb="1", **VRL_OPTS)

    cold = read_cobol(url, **kw)
    cold_io = io_counters(cold)
    assert cold_io["index_misses"] >= 1 and cold_io["index_saves"] >= 1
    assert cold_io["bytes_fetched"] >= len(data)

    warm = read_cobol(url, **kw)
    warm_io = io_counters(warm)
    assert warm_io["index_hits"] >= 1
    assert warm_io["index_misses"] == 0  # zero sequential index passes
    assert warm_io["block_hits"] >= 1
    assert warm_io["bytes_fetched"] == 0  # the network was never touched
    assert warm.to_arrow().equals(cold.to_arrow())

    # rewrite the remote object: fingerprint changes, both planes miss
    fs = fsspec.filesystem("memory")
    with fs.open(url[len("memory://"):], "wb") as f:
        f.write(vrl_payload(15000))
    changed = read_cobol(url, **kw)
    ch_io = io_counters(changed)
    assert ch_io["index_hits"] == 0 and ch_io["index_misses"] >= 1
    assert ch_io["bytes_fetched"] > 0
    assert changed.to_arrow().num_rows == 15000


def test_warm_fixed_rescan_serves_from_block_cache(tmp_path):
    cache = str(tmp_path / "cache")
    data = fixed_payload(5000)
    url = mem_write(data)
    kw = dict(copybook_contents=FIXED_COPYBOOK, cache_dir=cache,
              io_block_mb="0.05")
    cold = read_cobol(url, **kw)
    warm = read_cobol(url, **kw)
    assert io_counters(cold)["bytes_fetched"] >= len(data)
    assert io_counters(warm)["bytes_fetched"] == 0
    assert io_counters(warm)["block_hits"] >= 1
    assert warm.to_arrow().equals(cold.to_arrow())


def test_block_cache_lru_eviction_under_budget(tmp_path):
    """A tiny cache budget evicts oldest-touched blocks instead of
    growing without bound — and the scan still completes."""
    cache = str(tmp_path / "cache")
    data = fixed_payload(30000)  # 360 KB
    url = mem_write(data)
    out = read_cobol(url, copybook_contents=FIXED_COPYBOOK,
                     cache_dir=cache, cache_max_mb="0.1",
                     io_block_mb="0.02")
    assert len(out.to_rows()) == 30000
    io = io_counters(out)
    assert io["block_evictions"] >= 1
    # on-disk total respects the budget (within one block of slack for
    # in-flight writes)
    total = sum(os.path.getsize(os.path.join(dp, f))
                for dp, _, files in os.walk(cache) for f in files
                if f.endswith(".blk"))
    assert total <= int(0.1 * 1024 * 1024) + int(0.02 * 1024 * 1024)


def test_short_backend_fetch_never_misaligns_cached_blocks(tmp_path):
    """A backend serving FEWER bytes than size() promised (truncated
    object under an unchanged fingerprint) while LATER blocks sit in the
    cache: the read must surface a short read, never join the cached
    blocks after the gap (which would shift their bytes to wrong
    offsets — silent corruption)."""
    from cobrix_tpu.io.blockcache import CachingSource, shared_block_cache

    class _Mem:
        def __init__(self, payload, cut=None):
            self._p, self._cut = payload, cut

        def size(self):
            return len(self._p)

        def read(self, offset, n):
            if self._cut is not None:
                if offset >= self._cut:
                    return b""
                n = min(n, self._cut - offset)
            return self._p[offset:offset + n]

        def fingerprint(self):
            return "pinned"  # same generation before and after the cut

        name = "mem://short"

        def close(self):
            pass

    block = 100
    payload = bytes(range(256)) * 4  # 1024 B = 11 blocks
    cache = shared_block_cache(str(tmp_path / "c"), 0)
    warm = CachingSource(_Mem(payload), "mem://short", cache, block)
    assert warm.read(0, len(payload)) == payload  # caches every block

    # same generation, but storage now stops at byte 150 (mid-block 1);
    # blocks 2.. are cached. Evict blocks 0-1 so they must be refetched.
    gen = warm._gen_dir
    for start in (0, 100):
        os.unlink(os.path.join(gen, f"{start}-{start + block}.blk"))
    cut = CachingSource(_Mem(payload, cut=150), "mem://short", cache,
                        block)
    got = cut.read(0, len(payload))
    assert got == payload[:150]  # short, aligned prefix — NOT shifted


def test_local_backend_cache_invalidates_on_file_change(tmp_path):
    """The `local://` route (fsspec local filesystem through the full io
    stack): warm hit on unchanged file, structural invalidation when the
    file changes on disk."""
    cache = str(tmp_path / "cache")
    p = local_write(tmp_path, fixed_payload(2000))
    kw = dict(copybook_contents=FIXED_COPYBOOK, cache_dir=cache,
              io_block_mb="0.01")
    read_cobol("local://" + p, **kw)
    warm = read_cobol("local://" + p, **kw)
    assert io_counters(warm)["bytes_fetched"] == 0
    # a rewrite (different size) must miss the old generation
    with open(p, "wb") as f:
        f.write(fixed_payload(1000))
    changed = read_cobol("local://" + p, **kw)
    assert io_counters(changed)["bytes_fetched"] > 0
    assert changed.to_arrow().num_rows == 1000


_TWO_PROC_DRIVER = """
import json, sys
sys.path.insert(0, {repo!r})
from cobrix_tpu import read_cobol
out = read_cobol("local://" + {path!r},
                 copybook_contents={copybook!r},
                 cache_dir={cache!r}, io_block_mb="0.02",
                 is_record_sequence="true", is_rdw_big_endian="true",
                 input_split_size_mb="1")
io = out.metrics.as_dict().get("io") or {{}}
print(json.dumps({{"rows": out.to_arrow().num_rows, "io": io}}))
"""


def test_two_processes_share_one_cache_dir(tmp_path):
    """Concurrent cross-process cache access: two fresh processes scan
    the same file into the same cache_dir at once. Both must succeed
    with full row counts (atomic block writes: a reader never sees a
    torn block), and a third warm scan serves fully from the cache they
    built."""
    with hard_timeout(180, "two-process cache access"):
        cache = str(tmp_path / "cache")
        p = local_write(tmp_path, vrl_payload(30000))
        script = _TWO_PROC_DRIVER.format(
            repo=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            path=p, copybook=VRL_COPYBOOK, cache=cache)
        procs = [subprocess.Popen([sys.executable, "-c", script],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE,
                                  env=dict(os.environ,
                                           JAX_PLATFORMS="cpu"))
                 for _ in range(2)]
        results = []
        for proc in procs:
            stdout, stderr = proc.communicate(timeout=150)
            assert proc.returncode == 0, stderr.decode()[-2000:]
            results.append(json.loads(stdout))
        assert all(r["rows"] == 30000 for r in results)
        # the two processes converged on ONE generation of the file
        gen_dirs = os.listdir(os.path.join(cache, "blocks"))
        assert len(gen_dirs) == 1
        warm = read_cobol("local://" + p, copybook_contents=VRL_COPYBOOK,
                          cache_dir=cache, io_block_mb="0.02",
                          input_split_size_mb="1", **VRL_OPTS)
        io = io_counters(warm)
        assert io["bytes_fetched"] == 0 and io["index_hits"] >= 1


def test_flaky_backend_with_cache_and_prefetch(tmp_path):
    """The full stack at once: chaos faults below cache below prefetch.
    Retries recover, blocks persist, and the warm read never touches
    the flaky backend again."""
    scheme = f"chao{uuid.uuid4().hex[:8]}"
    cache = str(tmp_path / "cache")
    data = fixed_payload(3000)
    source = register_chaos_backend(scheme, data, fail_reads=2)
    kw = dict(copybook_contents=FIXED_COPYBOOK, cache_dir=cache,
              prefetch_blocks="2", io_block_mb="0.02",
              io_retry_attempts="5", io_retry_base_delay_ms="1")
    cold = read_cobol(f"{scheme}://f.dat", **kw)
    assert len(cold.to_rows()) == 3000
    assert cold.diagnostics.io_retries >= 2
    calls_after_cold = source.read_calls
    warm = read_cobol(f"{scheme}://f.dat", **kw)
    assert warm.to_arrow().equals(cold.to_arrow())
    assert source.read_calls == calls_after_cold  # served from disk
    assert io_counters(warm)["bytes_fetched"] == 0


# -- observability surface ----------------------------------------------


def test_io_counters_reach_metrics_and_prometheus(tmp_path):
    cache = str(tmp_path / "cache")
    out = read_cobol(mem_write(fixed_payload(2000)),
                     copybook_contents=FIXED_COPYBOOK, cache_dir=cache,
                     prefetch_blocks="2", io_block_mb="0.01")
    io = io_counters(out)
    assert io["bytes_fetched"] > 0 and io["block_misses"] > 0
    assert 0.0 <= io["prefetch_utilization"] <= 1.0
    text = prometheus_text()
    assert "cobrix_io_cache_events_total" in text
    assert "cobrix_io_remote_bytes_total" in text


# -- iocheck smoke (the prefetch x block grid stays behind `slow`) -------

def test_iocheck_quick():
    proc = subprocess.run(
        [sys.executable, "tools/iocheck.py", "--mb", "1"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


@pytest.mark.slow
def test_iocheck_sweep():
    proc = subprocess.run(
        [sys.executable, "tools/iocheck.py", "--mb", "4", "--sweep"],
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_local_plain_paths_never_touch_io_layer(tmp_path):
    """Plain local files keep the pre-io fast path: no io counters, no
    cache writes, even with the knobs set (the OS page cache IS the
    local block cache)."""
    p = local_write(tmp_path, fixed_payload(500))
    cache = str(tmp_path / "cache")
    out = read_cobol(p, copybook_contents=FIXED_COPYBOOK,
                     cache_dir=cache, prefetch_blocks="2")
    assert len(out.to_rows()) == 500
    assert "io" not in out.metrics.as_dict()
    assert not os.path.exists(os.path.join(cache, "blocks")) or \
        not os.listdir(os.path.join(cache, "blocks"))
