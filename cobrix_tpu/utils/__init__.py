"""Utility helpers: schema/data flattening and file scanning
(equivalents of the reference's SparkUtils / FileUtils)."""
from .flatten import convert_fields_to_strings, flatten_schema
from .file_utils import (
    find_non_divisible_files,
    get_number_of_files,
    list_input_files,
    total_size,
)

__all__ = [
    "convert_fields_to_strings",
    "flatten_schema",
    "find_non_divisible_files",
    "get_number_of_files",
    "list_input_files",
    "total_size",
]
