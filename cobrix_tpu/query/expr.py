"""Typed filter-expression AST with a string grammar and a JSON wire
form.

The AST is deliberately tiny — field refs, literals, the six
comparisons, AND/OR/NOT, ``isin`` and a segment-id match — because
everything in it must be *pushable*: each node knows how to evaluate
against decoded Arrow arrays (pushdown.py) and how to render as a
pyarrow compute expression (the dataset scan surface). Anything a
caller cannot say here they can still do post-hoc on the Arrow table.

Three interchangeable spellings, all accepted by the ``filter=``
option:

* builder:   ``col("SEGMENT_ID") == "C"``, ``col("AMOUNT") > 100``,
             ``col("ID").isin([1, 2]) & ~(col("NAME") == "X")``,
             ``segment_is("C", "P")``
* grammar:   ``SEGMENT_ID == 'C' and (AMOUNT > 100 or ID in (1, 2))``
             (``str(expr)`` round-trips through ``parse_filter``)
* JSON wire: ``{"op": "and", "args": [...]}`` — what
             ``ReaderParameters.filter`` carries, what resume-token and
             chunk-plan fingerprints hash, and what crosses the serve
             'R' frame unchanged.

Null semantics are SQL/Kleene (pyarrow's): a comparison against a null
value is null, AND/OR propagate three-valued logic, and a row whose
final predicate is null is DROPPED — identical to post-hoc
``table.filter(...)``, which parity tests pin.
"""
from __future__ import annotations

import json
import re
from typing import Iterable, List, Optional, Sequence, Tuple, Union

_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
_OP_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
            "==": "==", "!=": "!="}

LiteralValue = Union[str, int, float, bool, None]


class Expr:
    """Base filter-expression node."""

    def fields(self) -> List[str]:
        """Referenced field names, in first-appearance order, deduped."""
        out: List[str] = []
        self._collect_fields(out)
        seen = set()
        uniq = []
        for name in out:
            key = name.upper()
            if key not in seen:
                seen.add(key)
                uniq.append(name)
        return uniq

    def _collect_fields(self, out: List[str]) -> None:
        raise NotImplementedError

    def to_wire(self) -> dict:
        raise NotImplementedError

    def canonical(self) -> str:
        """Deterministic wire JSON — what fingerprints hash and what
        ``ReaderParameters.filter`` stores."""
        return json.dumps(self.to_wire(), sort_keys=True,
                          separators=(",", ":"))

    # -- combinators -------------------------------------------------------

    def __and__(self, other: "Expr") -> "Expr":
        return And(self, _as_expr(other))

    def __or__(self, other: "Expr") -> "Expr":
        return Or(self, _as_expr(other))

    def __invert__(self) -> "Expr":
        return Not(self)

    def __bool__(self):
        raise TypeError(
            "filter expressions are combined with & | ~ (bitwise), not "
            "'and'/'or'/'not' — Python cannot overload the keywords")

    def __repr__(self) -> str:
        return f"<query.Expr {self}>"

    def to_pyarrow(self):
        """The equivalent ``pyarrow.compute`` dataset expression."""
        raise NotImplementedError


class Field(Expr):
    """A field reference — only meaningful inside a comparison."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        name = str(name).strip()
        if not _NAME_RE.fullmatch(name):
            raise ValueError(f"invalid field name in filter: {name!r}")
        self.name = name

    # comparisons build Comparison nodes
    def __eq__(self, other):  # type: ignore[override]
        return Comparison("==", self.name, other)

    def __ne__(self, other):  # type: ignore[override]
        return Comparison("!=", self.name, other)

    def __lt__(self, other):
        return Comparison("<", self.name, other)

    def __le__(self, other):
        return Comparison("<=", self.name, other)

    def __gt__(self, other):
        return Comparison(">", self.name, other)

    def __ge__(self, other):
        return Comparison(">=", self.name, other)

    def __hash__(self):
        return hash(("field", self.name))

    def isin(self, values: Iterable[LiteralValue]) -> "IsIn":
        return IsIn(self.name, values)

    def _collect_fields(self, out: List[str]) -> None:
        out.append(self.name)

    def __str__(self) -> str:
        return self.name


class Literal(Expr):
    __slots__ = ("value",)

    def __init__(self, value: LiteralValue):
        _check_literal(value)
        self.value = value

    def _collect_fields(self, out: List[str]) -> None:
        pass

    def __str__(self) -> str:
        return _render_literal(self.value)


def _check_literal(value) -> None:
    if value is not None and not isinstance(value, (str, bool, int,
                                                    float)):
        # Decimal literals arrive as str/int/float; keeping the wire
        # form JSON-native keeps every surface (serve frames, tickets,
        # fingerprints) trivially serializable
        raise TypeError(
            f"unsupported filter literal {value!r} (type "
            f"{type(value).__name__}); use str/int/float/bool/None")


def _render_literal(v) -> str:
    if isinstance(v, str):
        return "'" + v.replace("\\", "\\\\").replace("'", "\\'") + "'"
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    return repr(v)


class Comparison(Expr):
    """``field <op> literal`` (op in ==, !=, <, <=, >, >=)."""

    __slots__ = ("op", "field", "value")

    def __init__(self, op: str, field: str, value: LiteralValue):
        if op not in _CMP_OPS:
            raise ValueError(f"unknown comparison operator {op!r}")
        _check_literal(value)
        if value is None and op not in ("==", "!="):
            raise ValueError(
                "null literals only support == / != (is-null tests)")
        self.op = op
        self.field = Field(field).name
        self.value = value

    def _collect_fields(self, out: List[str]) -> None:
        out.append(self.field)

    def to_wire(self) -> dict:
        return {"op": self.op, "field": self.field, "value": self.value}

    def __str__(self) -> str:
        return f"{self.field} {self.op} {_render_literal(self.value)}"

    def to_pyarrow(self):
        import pyarrow.compute as pc

        f = pc.field(self.field)
        if self.value is None:
            return f.is_null() if self.op == "==" else ~f.is_null()
        return {"==": f.__eq__, "!=": f.__ne__, "<": f.__lt__,
                "<=": f.__le__, ">": f.__gt__,
                ">=": f.__ge__}[self.op](self.value)


class IsIn(Expr):
    """``field in (v1, v2, ...)``."""

    __slots__ = ("field", "values")

    def __init__(self, field: str, values: Iterable[LiteralValue]):
        vals = tuple(values)
        if not vals:
            raise ValueError("isin needs at least one value")
        for v in vals:
            _check_literal(v)
            if v is None:
                raise ValueError("isin values cannot be null")
        self.field = Field(field).name
        self.values = vals

    def _collect_fields(self, out: List[str]) -> None:
        out.append(self.field)

    def to_wire(self) -> dict:
        return {"op": "in", "field": self.field,
                "values": list(self.values)}

    def __str__(self) -> str:
        inner = ", ".join(_render_literal(v) for v in self.values)
        return f"{self.field} in ({inner})"

    def to_pyarrow(self):
        import pyarrow.compute as pc

        return pc.field(self.field).isin(list(self.values))


class SegmentIs(Expr):
    """Match the configured multisegment id field against one or more
    segment ids — the predicate that pushes ALL the way down to raw
    record bytes in the chunk scan (depth 2), before any decode."""

    __slots__ = ("values",)

    def __init__(self, values: Iterable[str]):
        vals = tuple(str(v) for v in values)
        if not vals:
            raise ValueError("segment() needs at least one segment id")
        self.values = vals

    def _collect_fields(self, out: List[str]) -> None:
        pass  # resolved against the multisegment config at bind time

    def to_wire(self) -> dict:
        return {"op": "segment", "values": list(self.values)}

    def __str__(self) -> str:
        inner = ", ".join(_render_literal(v) for v in self.values)
        return f"segment({inner})"

    def to_pyarrow(self):
        raise TypeError(
            "segment() has no pyarrow equivalent (it names the "
            "multisegment id field implicitly); use a comparison on "
            "the segment id field instead")


def _flatten(cls, args: Sequence[Expr]) -> List[Expr]:
    out: List[Expr] = []
    for a in args:
        if isinstance(a, cls):
            out.extend(a.args)
        else:
            out.append(_as_expr(a))
    return out


class And(Expr):
    __slots__ = ("args",)

    def __init__(self, *args: Expr):
        self.args = tuple(_flatten(And, args))
        if len(self.args) < 2:
            raise ValueError("and needs at least two operands")

    def _collect_fields(self, out: List[str]) -> None:
        for a in self.args:
            a._collect_fields(out)

    def to_wire(self) -> dict:
        return {"op": "and", "args": [a.to_wire() for a in self.args]}

    def __str__(self) -> str:
        return "(" + " and ".join(str(a) for a in self.args) + ")"

    def to_pyarrow(self):
        out = self.args[0].to_pyarrow()
        for a in self.args[1:]:
            out = out & a.to_pyarrow()
        return out


class Or(Expr):
    __slots__ = ("args",)

    def __init__(self, *args: Expr):
        self.args = tuple(_flatten(Or, args))
        if len(self.args) < 2:
            raise ValueError("or needs at least two operands")

    def _collect_fields(self, out: List[str]) -> None:
        for a in self.args:
            a._collect_fields(out)

    def to_wire(self) -> dict:
        return {"op": "or", "args": [a.to_wire() for a in self.args]}

    def __str__(self) -> str:
        return "(" + " or ".join(str(a) for a in self.args) + ")"

    def to_pyarrow(self):
        out = self.args[0].to_pyarrow()
        for a in self.args[1:]:
            out = out | a.to_pyarrow()
        return out


class Not(Expr):
    __slots__ = ("arg",)

    def __init__(self, arg: Expr):
        self.arg = _as_expr(arg)

    def _collect_fields(self, out: List[str]) -> None:
        self.arg._collect_fields(out)

    def to_wire(self) -> dict:
        return {"op": "not", "arg": self.arg.to_wire()}

    def __str__(self) -> str:
        return f"not ({self.arg})"

    def to_pyarrow(self):
        return ~self.arg.to_pyarrow()


def _as_expr(x) -> Expr:
    if isinstance(x, Expr):
        if isinstance(x, (Field, Literal)):
            raise TypeError(
                f"{x!r} is not a predicate by itself; compare it "
                "(e.g. col('A') == 1)")
        return x
    raise TypeError(f"expected a filter expression, got {type(x).__name__}")


# -- builders ---------------------------------------------------------------

def col(name: str) -> Field:
    """A field reference: ``col("AMOUNT") > 100``."""
    return Field(name)


def lit(value: LiteralValue) -> Literal:
    return Literal(value)


def segment_is(*values: str) -> SegmentIs:
    """Segment-id match against the configured ``segment_field``."""
    return SegmentIs(values)


# -- wire form --------------------------------------------------------------

def from_wire(obj) -> Expr:
    """JSON wire dict (or its json.dumps string) -> Expr."""
    if isinstance(obj, str):
        obj = json.loads(obj)
    if not isinstance(obj, dict):
        raise ValueError(f"filter wire form must be an object, got "
                         f"{type(obj).__name__}")
    op = obj.get("op")
    try:
        if op in _CMP_OPS:
            # "value": null is an explicit is-null test; an ABSENT key
            # is a malformed object — silently reading it as null would
            # turn a client's dropped key into wrong rows
            if "value" not in obj:
                raise KeyError("value")
            return Comparison(op, obj["field"], obj["value"])
        if op == "in":
            return IsIn(obj["field"], obj["values"])
        if op == "segment":
            return SegmentIs(obj["values"])
        if op == "and":
            return And(*[from_wire(a) for a in obj["args"]])
        if op == "or":
            return Or(*[from_wire(a) for a in obj["args"]])
        if op == "not":
            return Not(from_wire(obj["arg"]))
    except KeyError as exc:
        # structurally incomplete wire JSON (e.g. a buggy serve client)
        # must surface as the option error it is, not a bare KeyError
        raise ValueError(
            f"filter wire object for op {op!r} is missing key "
            f"{exc.args[0]!r}") from exc
    raise ValueError(f"unknown filter op {op!r}")


# -- string grammar ---------------------------------------------------------

_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_\-:.]*")

_TOKEN_RE = re.compile(r"""
    \s*(
        (?P<op><=|>=|==|!=|=|<>|<|>)
      | (?P<lparen>\()
      | (?P<rparen>\))
      | (?P<comma>,)
      | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
      | (?P<num>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
      | (?P<name>[A-Za-z_][A-Za-z0-9_\-:.]*)
      | (?P<punct>[{}\[\]])
    )""", re.VERBOSE)

_KEYWORDS = {"and", "or", "not", "in", "true", "false", "null",
             "segment", "is_in", "invert"}


class _Tokens:
    def __init__(self, text: str):
        self.toks: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if m is None:
                if text[pos:].strip():
                    raise ValueError(
                        f"cannot tokenize filter at: {text[pos:]!r}")
                break
            pos = m.end()
            for kind in ("op", "lparen", "rparen", "comma", "str",
                         "num", "name", "punct"):
                v = m.group(kind)
                if v is not None:
                    self.toks.append((kind, v))
                    break
        self.i = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of filter expression")
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> bool:
        t = self.peek()
        if t is None or t[0] != kind:
            return False
        if value is not None and t[1].lower() != value:
            return False
        self.i += 1
        return True

    def expect(self, kind: str, value: Optional[str] = None) -> str:
        t = self.peek()
        if t is None or t[0] != kind or (
                value is not None and t[1].lower() != value):
            raise ValueError(
                f"expected {value or kind} at "
                f"{' '.join(v for _, v in self.toks[self.i:self.i + 4])!r}")
        self.i += 1
        return t[1]


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def _parse_value(toks: _Tokens) -> LiteralValue:
    kind, v = toks.next()
    if kind == "str":
        return _unquote(v)
    if kind == "num":
        return float(v) if ("." in v or "e" in v.lower()) else int(v)
    if kind == "name":
        low = v.lower()
        if low == "true":
            return True
        if low == "false":
            return False
        if low == "null":
            return None
        # a bare name here is ambiguous — most likely a FIELD reference
        # (e.g. the repr of a pyarrow field-to-field comparison), and
        # silently reading it as the string literal would return wrong
        # rows; rejecting it makes the dataset scanner take its
        # documented post-hoc fallback instead
        raise ValueError(
            f"expected a literal, got bare name {v!r} (quote string "
            "literals; field-to-field comparisons are not supported)")
    raise ValueError(f"expected a literal, got {v!r}")


def _parse_value_list(toks: _Tokens) -> List[LiteralValue]:
    toks.expect("lparen")
    values = [_parse_value(toks)]
    while toks.accept("comma"):
        values.append(_parse_value(toks))
    toks.expect("rparen")
    return values


def _parse_primary(toks: _Tokens) -> Expr:
    if toks.accept("name", "not") or toks.accept("name", "invert"):
        # `invert(...)`: pyarrow's repr spelling of ~
        return Not(_parse_primary(toks))
    if toks.accept("name", "segment"):
        return SegmentIs(str(v) for v in _parse_value_list(toks))
    t = toks.peek()
    if t is not None and t[0] == "name" and t[1].lower() == "is_in":
        # pyarrow repr: is_in(FIELD, {value_set=type:[v1, v2], ...})
        return _parse_pyarrow_is_in(toks)
    if toks.accept("lparen"):
        e = _parse_or(toks)
        toks.expect("rparen")
        return e
    kind, name = toks.next()
    if kind != "name" or name.lower() in _KEYWORDS:
        raise ValueError(f"expected a field name, got {name!r}")
    t = toks.peek()
    if t is not None and t[0] == "name" and t[1].lower() == "in":
        toks.next()
        return IsIn(name, _parse_value_list(toks))
    op = toks.expect("op")
    op = {"=": "==", "<>": "!="}.get(op, op)
    return Comparison(op, name, _parse_value(toks))


def _parse_pyarrow_is_in(toks: _Tokens) -> Expr:
    """``is_in(FIELD, {value_set=<type>:[v, ...], ...})`` — the repr of
    ``pc.field(F).isin([...])``, so pyarrow expressions round-trip
    through their string form into the pushdown pipeline."""
    toks.expect("name", "is_in")
    toks.expect("lparen")
    field = toks.expect("name")
    # everything between the comma and the matching ')' is the options
    # struct; pull the [...] value list out of the raw token stream
    depth = 1
    values: List[LiteralValue] = []
    saw_list = False
    while True:
        t = toks.next()
        if t[0] == "lparen":
            depth += 1
        elif t[0] == "rparen":
            depth -= 1
            if depth == 0:
                break
        elif t[0] in ("str", "num") and saw_list:
            values.append(
                _unquote(t[1]) if t[0] == "str"
                else (float(t[1]) if "." in t[1] or "e" in t[1].lower()
                      else int(t[1])))
        elif t[0] == "name" and t[1] == "value_set":
            saw_list = True
        elif t[0] == "name" and t[1] == "null_matching_behavior":
            saw_list = False
    if not values:
        raise ValueError("is_in(...) with an empty or unparseable "
                         "value_set")
    return IsIn(field, values)


def _parse_and(toks: _Tokens) -> Expr:
    args = [_parse_primary(toks)]
    while toks.accept("name", "and"):
        args.append(_parse_primary(toks))
    return args[0] if len(args) == 1 else And(*args)


def _parse_or(toks: _Tokens) -> Expr:
    args = [_parse_and(toks)]
    while toks.accept("name", "or"):
        args.append(_parse_and(toks))
    return args[0] if len(args) == 1 else Or(*args)


def parse_filter(text: str) -> Expr:
    """Parse the string grammar (or the JSON wire form) into an Expr.

    Grammar: ``FIELD op literal`` with ``== != < <= > >= = <>``,
    ``FIELD in (v1, v2)``, ``segment('C', 'P')``, ``and``/``or``/
    ``not``, parentheses. String literals quote with ``'`` or ``"``.
    The repr of a pyarrow compute expression over the same operators
    parses too (``(A == "x") and invert(B < 5)``, ``is_in(A, {...})``),
    which is how the dataset scanner lowers pyarrow filters.
    """
    text = text.strip()
    if not text:
        raise ValueError("empty filter expression")
    if text.startswith("{"):
        return from_wire(text)
    toks = _Tokens(text)
    e = _parse_or(toks)
    if toks.peek() is not None:
        rest = " ".join(v for _, v in toks.toks[toks.i:])
        raise ValueError(f"trailing tokens in filter: {rest!r}")
    return e


def normalize_filter(value) -> Optional[str]:
    """Any accepted filter spelling -> the canonical wire JSON string
    (None/'' -> None). The single normalization point: the option
    parser calls this, so ``ReaderParameters.filter`` always holds one
    deterministic form and resume-token/plan fingerprints never see
    two spellings of the same predicate."""
    if value is None:
        return None
    if isinstance(value, Expr):
        return value.canonical()
    text = str(value).strip()
    if not text:
        return None
    return parse_filter(text).canonical()
