"""Vectorized Arrow output: kernel column arrays -> pyarrow Table.

The reference feeds Spark per-record GenericRows because a Spark source
must (SparkCobolRowType.scala:24); a columnar framework emits Arrow arrays
straight from the kernel outputs instead. Numeric columns become typed
arrays from the (values, valid) numpy pairs without touching Python
objects; Decimal columns are built as decimal128 buffers from the int
mantissas; strings come from the LUT code-point matrix through one
vectorized trim + mask gather; OCCURS arrays become ListArrays whose
offsets derive from the DEPENDING-ON counts. Schema types follow the same
mapping as the output StructType (spark-cobol schema/CobolSchema.scala:
77-173): Decimal->decimal128(p,s), Integral->int32/int64 by precision
bucket, COMP-1/2->float32/float64, RAW->binary, OCCURS->list.

The fallback for anything the vectorized path can't express (host-fallback
codecs, truncated variable-length tails, non-ASCII code points, custom
charsets) is the per-column Python value list — same values, same nulls.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .. import native
from ..copybook.ast import Group, Primitive, Statement
from ..copybook.datatypes import Integral
from ..obs import fieldcost
from ..plan.compiler import Codec
from ..copybook.datatypes import SchemaRetentionPolicy, TrimPolicy
from .columnar import (
    _FLOAT_CODECS,
    _NATIVE_TRIM_MODES,
    _STRING_CODECS,
    _dyn_scale,
    _is_wide,
    _resolve_occurs,
    DecodedBatch,
    fixed_point_exponent,
)
from .schema import (
    ArrayType,
    Field,
    SimpleType,
    StructType,
    primitive_data_type,
)


def _pa():
    import pyarrow as pa
    return pa


def to_arrow_type(t):
    """Our schema type -> pyarrow type (decimal(p,s) strings included)."""
    pa = _pa()
    if isinstance(t, SimpleType):
        name = t.name
        if name == "string":
            return pa.string()
        if name == "integer":
            return pa.int32()
        if name == "long":
            return pa.int64()
        if name == "float":
            return pa.float32()
        if name == "double":
            return pa.float64()
        if name == "binary":
            return pa.binary()
        if name.startswith("decimal("):
            p, s = name[8:-1].split(",")
            return pa.decimal128(int(p), int(s))
        raise TypeError(f"Unknown simple type {name}")
    if isinstance(t, StructType):
        return pa.struct([(f.name, to_arrow_type(f.dtype)) for f in t.fields])
    if isinstance(t, ArrayType):
        return pa.list_(to_arrow_type(t.element))
    raise TypeError(t)


def arrow_schema(struct: StructType):
    # memoized on the StructType instance: per-chunk pipeline assembly
    # calls this once per chunk, and rebuilding a wide schema (exp1: 195
    # typed fields) is pure GIL-held overhead
    cached = getattr(struct, "_pa_schema", None)
    if cached is not None:
        return cached
    pa = _pa()
    schema = pa.schema([(f.name, to_arrow_type(f.dtype))
                        for f in struct.fields])
    try:
        struct._pa_schema = schema
    except AttributeError:  # slotted/frozen struct types stay uncached
        pass
    return schema


def _validity_buffer(valid: np.ndarray):
    pa = _pa()
    # py_buffer holds a reference to the packed array: zero-copy
    return pa.py_buffer(np.packbits(valid, bitorder="little"))


def _decimal128_from_mantissa(mantissa: np.ndarray, valid: np.ndarray,
                              pa_type):
    """decimal128 array with the int64 mantissa as the unscaled value."""
    pa = _pa()
    n = len(mantissa)
    le = np.zeros((n, 2), dtype="<i8")
    le[:, 0] = mantissa
    le[:, 1] = mantissa >> 63  # sign extension of the high limb
    vbuf = None if valid.all() else _validity_buffer(valid)
    return pa.Array.from_buffers(pa_type, n, [vbuf, pa.py_buffer(le)])


def _static_decimal_shift(spec, pa_type) -> Optional[int]:
    """Mantissa power-of-ten shift for a fixed-exponent decimal column
    (None when out of the exact-int64 0..18 window). The single source of
    the rule for the per-column, flat-OCCURS, and native-limb paths."""
    shift = pa_type.scale + fixed_point_exponent(spec)
    return shift if 0 <= shift <= 18 else None


def _numpy_dtype_for(pa_type):
    """pa numeric type -> the numpy dtype the kernels' outputs cast to."""
    pa = _pa()
    if pa.types.is_floating(pa_type):
        return np.float32 if pa.types.is_float32(pa_type) else np.float64
    return np.int32 if pa.types.is_int32(pa_type) else np.int64


# Java String.trim strips everything <= ' ' on both sides; left/right trim
# strip " \t" (scalar_decoders._trim parity)
_JAVA_TRIM = "".join(map(chr, range(0x21)))
_LR_TRIM = " \t"


def _string_from_codepoints(mat: np.ndarray, trimming: TrimPolicy):
    """[n, w] code points (uint8 masked ASCII or uint16 LUT output) -> Arrow
    string array. Requires every code point <= 0x7F so UTF-8 bytes == code
    points (the caller falls back otherwise); the fixed-width matrix becomes
    one zero-gather string buffer with uniform offsets, and trimming runs in
    Arrow's C++ kernels."""
    import pyarrow.compute as pc

    pa = _pa()
    n, w = mat.shape
    data = np.ascontiguousarray(mat.astype(np.uint8, copy=False))
    big = n * w > 2**31 - 8
    off_t, s_t = ("<i8", pa.large_string()) if big else ("<i4", pa.string())
    offsets = np.arange(n + 1, dtype=off_t) * w
    arr = pa.Array.from_buffers(
        s_t, n, [None, pa.py_buffer(offsets), pa.py_buffer(data)])
    if trimming is TrimPolicy.BOTH:
        arr = pc.utf8_trim(arr, characters=_JAVA_TRIM)
    elif trimming is TrimPolicy.LEFT:
        arr = pc.utf8_ltrim(arr, characters=_LR_TRIM)
    elif trimming is TrimPolicy.RIGHT:
        arr = pc.utf8_rtrim(arr, characters=_LR_TRIM)
    if big:
        arr = arr.cast(pa.string())
    return arr


_PA_LAZY_WARMED = False


def _warm_pa_lazy_imports() -> None:
    """Trigger pyarrow's lazy pandas-shim import outside any attribution
    region. The first masked `pa.array` call in a process imports pandas
    (~0.8s when installed); without this warm-up that one-time cost
    lands on whichever field happens to assemble first and tops the
    explain cost table with a lie. Only called when attribution is on —
    plain reads keep pyarrow's lazy behavior."""
    global _PA_LAZY_WARMED
    if _PA_LAZY_WARMED:
        return
    _PA_LAZY_WARMED = True
    pa = _pa()
    pa.array(np.zeros(1, dtype=np.int64), mask=np.array([True]))


def _asm_descriptor(spec, pa_type):
    """(kind, flags, dyn_sf, out_kind, dec_mode, shift, maxd) descriptor
    for the fused native decode->Arrow kernel, or None when the column's
    shape must keep its existing path. The rules mirror the per-column
    assembly routes byte for byte: same decode variants, same decimal
    shift/precision bounds (decimal128_batch), same fallback windows."""
    pa = _pa()
    codec = spec.codec
    p = spec.params
    wide = _is_wide(spec)
    if codec is Codec.BINARY:
        kind = (native.ASM_KIND_BINARY_WIDE if wide
                else native.ASM_KIND_BINARY)
        flags = int(bool(p.signed)) | (int(bool(p.big_endian)) << 1)
        dyn_sf = 0
    elif codec is Codec.BCD:
        kind = native.ASM_KIND_BCD_WIDE if wide else native.ASM_KIND_BCD
        flags = 0
        dyn_sf = 0
    elif codec in (Codec.DISPLAY_NUM, Codec.DISPLAY_NUM_ASCII):
        base = (native.ASM_KIND_DISPLAY_E if codec is Codec.DISPLAY_NUM
                else native.ASM_KIND_DISPLAY_A)
        kind = base + (4 if wide else 0)
        allow_dot = bool(p.explicit_decimal)
        # unconditional, matching columnar._variant_key: blank-filled
        # implied-point decimals decode to null, not 0.00
        require_digits = True
        flags = (int(bool(p.signed)) | (int(allow_dot) << 2)
                 | (int(require_digits) << 3))
        dyn_sf = min(p.scale_factor, 0)
    elif codec in _FLOAT_CODECS:
        kind = {Codec.FLOAT_IEEE: native.ASM_KIND_IEEE_F32,
                Codec.DOUBLE_IEEE: native.ASM_KIND_IEEE_F64,
                Codec.FLOAT_IBM: native.ASM_KIND_IBM_F32,
                Codec.DOUBLE_IBM: native.ASM_KIND_IBM_F64}[codec]
        flags = int(bool(p.big_endian)) << 1
        dyn_sf = 0
        wide = False
    else:
        return None

    dec_mode = native.ASM_DEC_STATIC
    shift = 0
    maxd = 0
    if pa.types.is_decimal(pa_type):
        if codec in _FLOAT_CODECS:
            return None
        out_kind = native.ASM_OUT_DECIMAL128
        if p.explicit_decimal or _dyn_scale(spec):
            if codec in (Codec.DISPLAY_NUM, Codec.DISPLAY_NUM_ASCII):
                # per-value exponent from the decoded dot_scale plane
                dec_mode = native.ASM_DEC_DOTS
                shift = pa_type.scale
            elif codec is Codec.BINARY and p.scale_factor < 0:
                # binary PIC P: exponent = |sf| + decimal digit count of
                # the value (columnar._binary_dyn_dots / _wide_dyn_dots)
                dec_mode = native.ASM_DEC_DIGIT_COUNT
                shift = pa_type.scale + p.scale_factor
            else:
                return None
        else:
            shift = pa_type.scale + fixed_point_exponent(spec)
            if not 0 <= shift <= 38:
                return None  # the per-column fallback owns this window
        # the same precision-bound rule as decimal128_batch callers: wide
        # limbs and >18-digit mantissas bound by the declared precision
        # (overflow -> exact-Decimal fallback); narrow <=18 stays unbounded
        maxd = pa_type.precision if (wide or pa_type.precision > 18) else 0
    elif pa.types.is_integer(pa_type):
        if wide or codec in _FLOAT_CODECS:
            return None
        if not (pa.types.is_int32(pa_type) or pa.types.is_int64(pa_type)):
            return None
        out_kind = (native.ASM_OUT_INT32 if pa.types.is_int32(pa_type)
                    else native.ASM_OUT_INT64)
    elif pa.types.is_floating(pa_type):
        if codec not in _FLOAT_CODECS:
            return None
        is_f32 = pa.types.is_float32(pa_type)
        # the decode width must match the output width exactly (the
        # kernel writes the decoded float in its natural precision)
        if is_f32 != (codec in (Codec.FLOAT_IEEE, Codec.FLOAT_IBM)):
            return None
        out_kind = (native.ASM_OUT_FLOAT32 if is_f32
                    else native.ASM_OUT_FLOAT64)
    else:
        return None
    return (kind, flags, dyn_sf, out_kind, dec_mode, shift, maxd)


class ArrowBatchBuilder:
    """Builds Arrow arrays for one DecodedBatch — either a single active
    segment (`active`), or a decode-once whole-plan batch where
    `redefine_masks` gates each segment redefine per row via the struct
    validity bitmap (inactive rows' decoded bytes are garbage, but a null
    parent struct masks its children by Arrow semantics)."""

    def __init__(self, batch: DecodedBatch, active: Optional[str],
                 redefine_masks: Optional[dict] = None):
        self.batch = batch
        self.decoder = batch.decoder
        self.active = active
        self.redefine_masks = redefine_masks
        self.n = batch.n_records
        # per-field cost attribution (None = off): the per-column
        # assembly step is timed at column granularity; nested regions
        # (string transcode / decimal128 group builds triggered inside
        # a column's build) charge their own time, not the column's.
        # Taken from the BATCH (captured at decode time), not the obs
        # context — sequential `to_arrow` runs after the read's context
        # deactivated, and CobolData's pooled table builds run on
        # threads that never activated it
        self.fc = batch.field_costs
        if self.fc is not None:
            _warm_pa_lazy_imports()

    # -- leaves ------------------------------------------------------------

    def leaf_strings_at(self, sts, positions: np.ndarray) -> dict:
        """String-leaf values built AT `positions` straight from the raw
        file image, for EVERY eligible statement of one struct in ONE
        subset kernel call (per-column calls paid the wrapper/gather
        overhead once per leaf). Returns {id(st): pa.StringArray} for the
        leaves it could build; callers fall back to the full-length
        build + take for the rest (non-EBCDIC codecs, no raw image,
        truncated rows, native library unavailable)."""
        from .. import native

        pa = _pa()
        rs = self.batch.raw_source
        trim = _NATIVE_TRIM_MODES.get(self.decoder.plan.trimming)
        if rs is None or trim is None or not native.available():
            return {}
        buf, offs, lens = rs
        sub_offs = sub_lens = None
        chosen, specs = [], []
        for st in sts:
            col = self.decoder.slot_map.get((id(st), ()))
            if col is None:
                continue
            spec = self.decoder.plan.columns[col]
            if spec.codec is not Codec.EBCDIC_STRING:
                continue
            if sub_lens is None:
                sub_offs = offs[positions]
                sub_lens = lens[positions]
            if bool((sub_lens < spec.offset + spec.width).any()):
                continue  # truncated tails keep the scalar-owned path
            chosen.append(st)
            specs.append(spec)
        if not specs:
            return {}
        res = native.string_cols_arrow_raw(
            buf, sub_offs, sub_lens,
            np.asarray([sp.offset for sp in specs], dtype=np.int64),
            np.asarray([sp.width for sp in specs], dtype=np.int64),
            self.decoder.lut, trim)
        out = {}
        if res:
            for st, r in zip(chosen, res):
                if r is None:
                    continue
                offsets, data = r
                out[id(st)] = pa.Array.from_buffers(
                    pa.string(), len(positions),
                    [None, pa.py_buffer(offsets), pa.py_buffer(data)])
        return out

    def leaf_numeric_at(self, st: Primitive, positions: np.ndarray):
        """Integer/float leaf values gathered AT `positions` — the numpy
        gather happens before the Arrow build instead of a full-length
        array + take. None -> caller uses the full path (decimals, wide
        planes, truncation, host fallback)."""
        pa = _pa()
        col = self.decoder.slot_map.get((id(st), ()))
        if col is None:
            return None
        spec = self.decoder.plan.columns[col]
        pa_type = to_arrow_type(primitive_data_type(st))
        if not (pa.types.is_integer(pa_type)
                or pa.types.is_floating(pa_type)):
            return None
        if spec.codec in _STRING_CODECS:
            return None
        lengths = self.batch.lengths
        if lengths is not None and bool(
                (lengths[positions] < spec.offset + spec.width).any()):
            return None  # truncated tails keep the scalar-owned path
        out = self.batch.column_arrays(col)
        if "values" not in out or "values_hi" in out:
            return None
        values = np.asarray(out["values"])[positions]
        valid = np.asarray(out["valid"])[positions]
        return pa.array(
            values.astype(_numpy_dtype_for(pa_type), copy=False),
            mask=None if valid.all() else ~valid)

    def _relevant_of(self, spec):
        """Row-visibility mask for a column of a decode-once batch (None =
        visible everywhere)."""
        if self.redefine_masks is not None and spec.segment:
            return self.redefine_masks.get(spec.segment.upper())
        return None

    def _python_fallback(self, col: int, pa_type, relevant=None):
        pa = _pa()
        # `relevant` (decode-once batches): rows hidden by a null parent
        # struct materialize as None and skip the truncation fixups
        return pa.array(self.batch.column_values(col, relevant=relevant),
                        type=pa_type)

    # -- fused native assembly (decode -> Arrow buffers in one pass) -------

    def _asm_call(self, specs, descs, out_ptrs, out_strides, valid_ptrs,
                  valid_strides, row_masks=None, rows=None):
        """One fused-kernel invocation over prepared destinations: the
        GIL is released for the whole decode+assemble pass. `row_masks`:
        per-spec row-visibility masks (decode-once redefines) — hidden
        rows emit null in-kernel without decoding. `rows`: decode ONLY
        these record indices (compact output of len(rows) rows — the
        destinations must be sized for that). Returns the per-column ok
        array, or None when the library is unavailable."""
        batch = self.batch
        k = len(specs)
        col_offsets = np.fromiter((s.offset for s in specs), np.int64, k)
        widths = np.fromiter((s.width for s in specs), np.int32, k)
        kinds = np.fromiter((d[0] for d in descs), np.int32, k)
        flags = np.fromiter((d[1] for d in descs), np.int32, k)
        dyn_sfs = np.fromiter((d[2] for d in descs), np.int32, k)
        out_kinds = np.fromiter((d[3] for d in descs), np.int32, k)
        dec_modes = np.fromiter((d[4] for d in descs), np.int32, k)
        shifts = np.fromiter((d[5] for d in descs), np.int64, k)
        maxds = np.fromiter((d[6] for d in descs), np.int32, k)
        rs = batch.raw_source
        if rs is not None:
            src, offs, lens = rs
            extent = src.size
            if rows is not None:
                offs = offs[rows]
                lens = lens[rows]
        else:
            if rows is not None:
                return None  # packed source: subsetting would copy bytes
            src = np.ascontiguousarray(batch.data)
            offs = lens = None
            extent = src.shape[1] if src.ndim == 2 else 0
        n = self.n if rows is None else len(rows)
        ok = native.assemble_cols_arrow(
            src, offs, lens, extent, col_offsets, widths, kinds, flags,
            dyn_sfs, out_kinds, dec_modes, shifts, maxds,
            out_ptrs, out_strides, valid_ptrs, valid_strides, n,
            row_masks=row_masks)
        if ok is not None and batch.pass_counts is not None:
            batch.pass_counts.incr("fused_assembly")
        return ok

    def _native_scalar_array(self, col: int):
        """pa.Array for a scalar (non-OCCURS-slot) numeric/float column
        from the batch-wide fused assembly, or None (ineligible column,
        exact-Decimal fallback, library unavailable). The first call
        assembles EVERY eligible deferred column of the batch in one
        native pass; later leaves hit the cache."""
        cache = self.batch._asm_cache
        if cache is None:
            cache = self._build_native_scalars()
            self.batch._asm_cache = cache
        return cache.get(col)

    def _build_native_scalars(self) -> dict:
        batch = self.batch
        if not native.available():
            return {}
        entries = []
        lengths = batch.lengths
        for c in self.decoder.plan.columns:
            if c.slot_path or c.statement is None:
                continue
            out = batch._out.get(c.index)
            if out is None or "lazy_numeric" not in out:
                continue  # planes already exist: existing routes serve them
            pa_type = to_arrow_type(primitive_data_type(c.statement))
            desc = _asm_descriptor(c, pa_type)
            if desc is None:
                continue
            relevant = self._relevant_of(c)
            if lengths is not None:
                trunc = lengths < c.offset + c.width
                if relevant is not None:
                    trunc = trunc & relevant
                if bool(trunc.any()):
                    continue  # the scalar path owns partial-field rules
            # masked columns ride the same pass: their row mask reaches
            # the kernel, which emits null for hidden rows WITHOUT
            # decoding them — so garbage under another redefine arm can
            # neither leak values nor trip the decimal exactness bail
            entries.append((c, pa_type, desc, relevant))
        if not entries:
            return {}
        fc = self.fc
        tok = fc.begin() if fc is not None else None
        arrays = self._assemble_scalar_entries(entries)
        if tok is not None:
            plan = self.decoder.plan
            # coarse per-pass timing split by bytes touched, taken in
            # Python around the GIL-released native call — explain's
            # assemble plane keeps seeing native assembly. The kernel
            # label keeps the per-codec family (the explain table's
            # "which kernel decodes this field" contract). Columns the
            # pass could NOT serve (decimal ok=False) are excluded:
            # their fallback rebuild re-times itself, and charging them
            # here too would double-count (the fieldcost discard rule)
            served = [c for c, _, _, _ in entries if c.index in arrays]
            if served:
                fc.commit_weighted(
                    tok,
                    [((plan.cost_name(c),), c.width, self.n * c.width,
                      f"{c.codec.value}/w{c.width}") for c in served],
                    fieldcost.PLANE_ASSEMBLE, self.n)
            else:
                fc.discard(tok)
        return arrays

    def _assemble_scalar_entries(self, entries) -> dict:
        pa = _pa()
        n = self.n
        k = len(entries)
        bufs, valids = [], []
        out_ptrs = np.empty(k, dtype=np.uintp)
        out_strides = np.empty(k, dtype=np.int64)
        valid_ptrs = np.empty(k, dtype=np.uintp)
        valid_strides = np.ones(k, dtype=np.int64)
        for j, (c, pa_type, d, _m) in enumerate(entries):
            out_kind = d[3]
            if out_kind == native.ASM_OUT_DECIMAL128:
                buf = np.empty((n, 16), dtype=np.uint8)
            else:
                buf = np.empty(n, dtype=native.ASM_OUT_DTYPE[out_kind])
            valid = np.empty(n, dtype=np.uint8)
            bufs.append(buf)
            valids.append(valid)
            out_ptrs[j] = buf.ctypes.data
            out_strides[j] = native.ASM_OUT_ITEMSIZE[out_kind]
            valid_ptrs[j] = valid.ctypes.data
        masks = [m for _, _, _, m in entries]
        ok = self._asm_call([c for c, _, _, _ in entries],
                            [d for _, _, d, _ in entries],
                            out_ptrs, out_strides, valid_ptrs,
                            valid_strides,
                            row_masks=(masks if any(m is not None
                                                    for m in masks)
                                       else None))
        if ok is None:
            return {}
        result = {}
        for j, (c, pa_type, d, _m) in enumerate(entries):
            if not ok[j]:
                continue  # exact-Decimal fallback rebuilds this column
            packed = native.pack_validity(valids[j])
            if packed is None:
                break
            bitmap, nulls = packed
            vbuf = None if nulls == 0 else pa.py_buffer(bitmap)
            result[c.index] = pa.Array.from_buffers(
                pa_type, n, [vbuf, pa.py_buffer(bufs[j])],
                null_count=nulls)
        return result

    def _native_flat_values(self, st, cols, spec0, pa_type, max_size: int,
                            row_mask=None, compact_rows=None):
        """Record-major flat values array for ALL slots of one OCCURS
        numeric leaf via the fused kernel: every slot column writes into
        one shared buffer (slot s of row i at i*S+s) with one shared
        validity plane — the per-slot stack/astype/pack glue disappears.
        `row_mask`: decode-once row visibility for the owning segment
        (hidden rows emit null in-kernel, never decoded). `compact_rows`:
        decode ONLY these visible rows into a len(rows)*S values array —
        the caller gives hidden rows empty lists under their null parent
        struct, so the kernel never touches (or sizes buffers for) them.
        None -> caller's existing paths."""
        batch = self.batch
        if not native.available():
            return None
        outm = batch._out
        for c in cols:
            o = outm.get(c)
            if o is None or "lazy_numeric" not in o:
                return None  # planes exist: the stack path serves them
        key = (id(st), cols[0], compact_rows is None)
        cached = batch._asm_flat_cache.get(key)
        if cached is not None:
            return cached
        desc = _asm_descriptor(spec0, pa_type)
        if desc is None:
            return None
        pa = _pa()
        n = self.n if compact_rows is None else len(compact_rows)
        total = n * max_size
        out_kind = desc[3]
        item = native.ASM_OUT_ITEMSIZE[out_kind]
        if out_kind == native.ASM_OUT_DECIMAL128:
            flat = np.empty((total, 16), dtype=np.uint8)
        else:
            flat = np.empty(total, dtype=native.ASM_OUT_DTYPE[out_kind])
        valid = np.empty(total, dtype=np.uint8)
        k = len(cols)
        base = int(flat.ctypes.data)
        vbase = int(valid.ctypes.data)
        out_ptrs = np.fromiter((base + j * item for j in range(k)),
                               np.uintp, k)
        out_strides = np.full(k, max_size * item, dtype=np.int64)
        valid_ptrs = np.fromiter((vbase + j for j in range(k)),
                                 np.uintp, k)
        valid_strides = np.full(k, max_size, dtype=np.int64)
        specs = [self.decoder.plan.columns[c] for c in cols]
        fc = self.fc
        tok = fc.begin() if fc is not None else None
        ok = self._asm_call(specs, [desc] * k, out_ptrs, out_strides,
                            valid_ptrs, valid_strides,
                            row_masks=([row_mask] * k
                                       if row_mask is not None else None),
                            rows=compact_rows)
        arr = None
        if ok is not None and bool(ok.all()):
            packed = native.pack_validity(valid)
            if packed is not None:
                bitmap, nulls = packed
                vb = None if nulls == 0 else pa.py_buffer(bitmap)
                arr = pa.Array.from_buffers(
                    pa_type, total, [vb, pa.py_buffer(flat)],
                    null_count=nulls)
        if tok is not None:
            if arr is not None:
                fc.commit(tok, (self.decoder.plan.cost_name(spec0),),
                          fieldcost.PLANE_ASSEMBLE, n * spec0.width * k,
                          n * k, f"{spec0.codec.value}/w{spec0.width}")
            else:
                # failed fused attempt: the fallback path re-times this
                # plane; charging both would double-count it
                fc.discard(tok)
        if arr is not None:
            batch._asm_flat_cache[key] = arr
        return arr

    def _leaf_array(self, st: Primitive, slot_path):
        pa = _pa()
        pa_type = to_arrow_type(primitive_data_type(st))
        col = self.decoder.slot_map.get((id(st), slot_path))
        if col is None:
            return pa.nulls(self.n, type=pa_type)
        spec = self.decoder.plan.columns[col]
        fc = self.fc
        if fc is None:
            return self._leaf_array_impl(st, col, spec, pa_type)
        tok = fc.begin()
        arr = self._leaf_array_impl(st, col, spec, pa_type)
        # seconds only: the field's bytes/values were already counted by
        # the decode (or string-transcode) call that produced the planes
        fc.commit(tok, (self.decoder.plan.cost_name(spec),),
                  fieldcost.PLANE_ASSEMBLE, 0, 0)
        return arr

    def _leaf_array_impl(self, st: Primitive, col: int, spec, pa_type):
        pa = _pa()
        # rows where this column is visible: in a decode-once batch a
        # redefine-gated column only matters where its segment is active
        # (elsewhere the parent struct is null and the decoded bytes are
        # garbage by design)
        relevant = None
        if self.redefine_masks is not None and spec.segment:
            relevant = self.redefine_masks.get(spec.segment.upper())
        lengths = self.batch.lengths
        if lengths is not None:
            trunc = lengths < spec.offset + spec.width
            if relevant is not None:
                trunc = trunc & relevant
            if bool(trunc.any()):
                # truncated variable-length tails: the scalar path owns
                # the partial-field rules
                return self._python_fallback(col, pa_type, relevant)
        if spec.codec not in _STRING_CODECS:
            # fused one-pass native assembly: deferred numeric columns
            # decode straight into this column's Arrow buffers
            arr = self._native_scalar_array(col)
            if arr is not None:
                return arr
        if spec.codec in _STRING_CODECS:
            # one-pass native transcode+trim straight into Arrow buffers
            # (no code-point matrix, no Arrow trim kernel)
            bufs = self.batch.string_arrow_buffers(
                spec, relevant_of=self._relevant_of)
            if bufs is not None:
                offsets, data = bufs
                return pa.Array.from_buffers(
                    pa.string(), self.n,
                    [None, pa.py_buffer(offsets), pa.py_buffer(data)])
        out = self.batch.column_arrays(col)
        if "host" in out:
            return self._python_fallback(col, pa_type, relevant)
        if "values_hi" in out:
            # wide uint128-limb columns: native decimal128 build from the
            # limbs (one batched call per kernel group when possible);
            # exact-Decimal fallback when any value needs rounding or
            # outruns the declared precision
            arr = self._decimal_group_array(spec, pa_type)
            if arr is None:
                arr = self._decimal128_native(spec, out, pa_type, relevant,
                                              wide=True)
            if arr is not None:
                return arr
            return self._python_fallback(col, pa_type, relevant)
        if spec.codec in _STRING_CODECS:
            return self._string_array(spec, out, pa_type, relevant)
        if spec.codec in _FLOAT_CODECS:
            values = np.asarray(out["values"])
            valid = np.asarray(out["valid"])
            return pa.array(
                values.astype(_numpy_dtype_for(pa_type), copy=False),
                mask=~valid if not valid.all() else None)
        # fixed-point
        values = np.asarray(out["values"])
        valid = np.asarray(out["valid"])
        mask = None if valid.all() else ~valid
        if pa.types.is_integer(pa_type):
            return pa.array(
                values.astype(_numpy_dtype_for(pa_type), copy=False),
                mask=mask)
        if pa.types.is_decimal(pa_type):
            arr = self._decimal_group_array(spec, pa_type)
            if arr is not None:
                return arr
            if pa_type.precision > 18:
                # int64 mantissa widened into 128-bit limbs natively
                arr = self._decimal128_native(spec, out, pa_type, relevant,
                                              wide=False)
                if arr is not None:
                    return arr
                return self._python_fallback(col, pa_type, relevant)
            mantissa = values.astype(np.int64, copy=False)
            if spec.params.explicit_decimal or _dyn_scale(spec):
                shift = pa_type.scale - np.asarray(out["dot_scale"],
                                                   dtype=np.int64)
                shift = np.broadcast_to(shift, mantissa.shape)
                if relevant is not None:
                    # garbage dot-scale planes in hidden rows must neither
                    # force the fallback nor feed negative powers below
                    shift = np.where(relevant, shift, 0)
                if np.any((shift < 0) | (shift > 18)):
                    return self._python_fallback(col, pa_type, relevant)
            else:
                shift = _static_decimal_shift(spec, pa_type)
                if shift is None:
                    return self._python_fallback(col, pa_type, relevant)
            mantissa = mantissa * 10 ** shift
            return _decimal128_from_mantissa(mantissa, valid, pa_type)
        return self._python_fallback(col, pa_type, relevant)

    def _decimal_group_array(self, spec, pa_type):
        """Per-column decimal128 array served from ONE native build per
        kernel group (native.decimal128_batch): the group's column planes
        are stacked and shifted/packed in a single call, replacing
        per-column wrapper calls + strided copies — the dominant GIL-held
        assembly cost on decimal-heavy profiles, and what lets pipeline
        workers overlap instead of serializing on the interpreter. None ->
        caller's per-column paths (masked rows, host fallback, ok=0
        exact-fallback columns, native library unavailable)."""
        from .. import native

        if not native.available():
            return None
        if self._relevant_of(spec) is not None:
            return None
        g = self.decoder.group_of_col.get(spec.index)
        if g is None or len(g.columns) < 2:
            return None  # single column: the per-column kernel is enough
        cache = self.batch._arrow_dec_cache
        entry = cache.get(id(g))
        if entry is None:
            entry = self._build_decimal_group(g)
            cache[id(g)] = entry
        return entry.get(spec.index)

    def _build_decimal_group(self, g) -> dict:
        """{col index -> pa.Array | None} for every decimal-typed column
        of one kernel group, via one decimal128_batch call."""
        fc = self.fc
        if fc is None:
            return self._build_decimal_group_impl(g)
        tok = fc.begin()
        entry = self._build_decimal_group_impl(g)
        plan = self.decoder.plan
        names = tuple(plan.cost_name(c) for c in g.columns
                      if c.index in entry) or g.names
        fc.commit(tok, names, fieldcost.PLANE_ASSEMBLE, 0, 0, g.label)
        return entry

    def _build_decimal_group_impl(self, g) -> dict:
        from .. import native

        pa = _pa()
        entry: dict = {}
        chosen = []
        for c in g.columns:
            if c.statement is None:
                continue
            if self.redefine_masks is not None and c.segment:
                continue  # masked columns keep the per-column path
            pa_t = to_arrow_type(primitive_data_type(c.statement))
            if not pa.types.is_decimal(pa_t):
                continue
            out = self.batch._out.get(c.index)
            if out is None or "values" not in out or "host" in out:
                continue
            use_dots = bool(c.params.explicit_decimal or _dyn_scale(c))
            if use_dots and "dot_scale" not in out:
                continue
            chosen.append((c, pa_t, out, use_dots))
        if not chosen:
            return entry
        n = self.n
        k = len(chosen)
        wide = "values_hi" in chosen[0][2]
        valid = np.stack([np.asarray(o["valid"])
                          for _, _, o, _ in chosen]).astype(np.uint8,
                                                            copy=False)
        if wide:
            hi = np.stack([np.asarray(o["values_hi"], dtype=np.uint64)
                           for _, _, o, _ in chosen])
            lo = np.stack([np.asarray(o["values"], dtype=np.uint64)
                           for _, _, o, _ in chosen])
            neg = np.stack([np.asarray(o["negative"])
                            for _, _, o, _ in chosen]).astype(np.uint8,
                                                              copy=False)
            values = None
        else:
            hi = lo = neg = None
            values = np.stack([np.asarray(o["values"])
                               for _, _, o, _ in chosen]).astype(
                np.int64, copy=False)
        use_dots_arr = np.asarray([ud for _, _, _, ud in chosen],
                                  dtype=np.uint8)
        dots = None
        if use_dots_arr.any():
            dots = np.zeros((k, n), dtype=np.int64)
            for j, (_, _, o, ud) in enumerate(chosen):
                if ud:
                    dots[j] = np.asarray(o["dot_scale"], dtype=np.int64)
        shifts = np.asarray(
            [pa_t.scale if ud
             else pa_t.scale + fixed_point_exponent(c)
             for c, pa_t, _, ud in chosen], dtype=np.int64)
        # precision bounds mirror the per-column paths exactly: wide limbs
        # and >18-digit narrow columns went through the native kernel with
        # max_digits=precision (overflow -> exact fallback, which
        # surfaces it); only the <=18 narrow numpy-mantissa path never
        # bounded, so maxd=0 keeps that behavior there
        maxd = np.asarray(
            [pa_t.precision if (wide or pa_t.precision > 18) else 0
             for _, pa_t, _, _ in chosen], dtype=np.int32)
        res = native.decimal128_batch(hi, lo, values, neg, valid, dots,
                                      use_dots_arr, shifts, maxd)
        if res is None:
            return entry
        data, ok = res
        for j, (c, pa_t, _, _) in enumerate(chosen):
            if not ok[j]:
                entry[c.index] = None
                continue
            vcol = valid[j].view(bool)
            vbuf = None if vcol.all() else _validity_buffer(vcol)
            entry[c.index] = pa.Array.from_buffers(
                pa_t, n, [vbuf, pa.py_buffer(data[j])])
        return entry

    def _decimal128_native(self, spec, out, pa_type, relevant, wide: bool):
        """decimal128 buffers straight from the kernel outputs via the
        native 128-bit shift-and-pack; None -> caller falls back to exact
        Decimal materialization."""
        from .. import native

        pa = _pa()
        if not native.available() or not pa.types.is_decimal(pa_type):
            return None
        valid = np.asarray(out["valid"])
        if relevant is not None:
            valid = valid & relevant
        if wide:
            hi = np.asarray(out["values_hi"])
            lo = np.asarray(out["values"])
            neg = np.asarray(out["negative"])
        else:
            v = np.asarray(out["values"]).astype(np.int64, copy=False)
            neg = v < 0
            # |INT64_MIN| wraps under int64 abs; the uint64 view of the
            # wrapped value is the correct 2^63 magnitude
            lo = np.abs(v).view(np.uint64)
            hi = np.zeros_like(lo)
        if spec.params.explicit_decimal or _dyn_scale(spec):
            shifts = pa_type.scale - np.asarray(out["dot_scale"],
                                                dtype=np.int64)
        else:
            shifts = np.int64(pa_type.scale + fixed_point_exponent(spec))
        res = native.decimal128_from_limbs(hi, lo, neg, valid, shifts,
                                           max_digits=pa_type.precision)
        if res is None:
            return None
        data, ok = res
        if not bool(ok.all()):
            return None
        vbuf = None if valid.all() else _validity_buffer(valid)
        return pa.Array.from_buffers(pa_type, len(valid),
                                     [vbuf, pa.py_buffer(data)])

    def _string_array(self, spec, out, pa_type, relevant=None):
        pa = _pa()
        if not self.batch._vectorizable_string(spec):
            # UTF-16 / HEX / RAW / custom charsets: per-value host decode
            return self._python_fallback(spec.index, pa_type, relevant)
        mat = out["bytes"]
        if mat.ndim != 2 or mat.shape[1] == 0:
            return pa.array([""] * self.n, type=pa_type)
        if relevant is not None and not relevant.all():
            # hidden rows' garbage code points must not poison the
            # column (their >0x7F values would truncate to invalid
            # UTF-8 below) — blank them; the null parent struct hides
            # whatever value they produce
            mat = mat.copy()
            mat[~relevant] = 0x20
        if mat.dtype == np.uint16 and bool((mat > 0x7F).any()):
            # non-ASCII code points need real UTF-8 encoding
            return self._python_fallback(spec.index, pa_type, relevant)
        return _string_from_codepoints(mat, self.decoder.plan.trimming)

    # -- arrays / groups ---------------------------------------------------

    def _occurs_counts(self, st: Statement) -> Optional[np.ndarray]:
        """Per-record element counts, or None when constant max size."""
        if st.depending_on is None:
            return None
        dep_col = self.decoder.dependee_columns.get(st.depending_on)
        if dep_col is None:
            return None
        values = self.batch.column_values(dep_col)
        if st.depending_on_handlers or any(
                not isinstance(v, (int, np.integer)) for v in values):
            return np.asarray([_resolve_occurs(st, v) for v in values],
                              dtype=np.int64)
        v = np.asarray(values, dtype=np.int64)
        return np.where((v >= st.array_min_size) & (v <= st.array_max_size),
                        v, st.array_max_size)

    def _flat_slot_values(self, st: Primitive, slot_path, max_size: int,
                          compact_mask=None, compact_rows=None):
        """One record-major flat array covering every OCCURS slot of a
        numeric leaf (the slots live in one kernel group; per-slot
        pa.array calls would dominate wide-OCCURS materialization —
        exp3's 2000-element plane is 4000 such calls otherwise).
        `compact_mask`/`compact_rows` (decode-once): build values for
        ONLY the visible rows — the caller verified hidden rows are
        nulled at an enclosing struct, where child buffers are invisible.
        None -> caller uses the per-slot path."""
        pa = _pa()
        pa_type = to_arrow_type(primitive_data_type(st))
        is_decimal = pa.types.is_decimal(pa_type)
        if not (pa.types.is_integer(pa_type) or pa.types.is_floating(pa_type)
                or is_decimal):
            return None
        cols = [self.decoder.slot_map.get((id(st), slot_path + (k,)))
                for k in range(max_size)]
        if any(c is None for c in cols):
            return None
        spec0 = self.decoder.plan.columns[cols[0]]
        relevant = self._relevant_of(spec0)
        if compact_mask is not None and relevant is not compact_mask:
            return None  # leaf belongs to a different segment arm
        if is_decimal and (spec0.params.explicit_decimal
                           or _dyn_scale(spec0)):
            return None  # per-value exponent planes stay per slot
        lengths = self.batch.lengths
        if lengths is not None:
            last = self.decoder.plan.columns[cols[-1]]
            trunc = lengths < last.offset + last.width
            if relevant is not None:
                trunc = trunc & relevant
            if bool(trunc.any()):
                return None  # truncated tails own the partial-field rules
        if compact_rows is not None:
            return self._native_flat_values(st, cols, spec0, pa_type,
                                            max_size,
                                            compact_rows=compact_rows)
        arr = self._native_flat_values(st, cols, spec0, pa_type, max_size,
                                       row_mask=relevant)
        if arr is not None:
            return arr
        if relevant is not None:
            # no native pass: hidden rows would need Python-side blanking
            # — keep the existing masked per-slot route
            return None
        if is_decimal and pa_type.precision > 18:
            return None  # the stack path below is exact-int64 only
        outs = [self.batch.column_arrays(c) for c in cols]
        if any("values" not in o or "values_hi" in o for o in outs):
            return None
        vals = np.stack([o["values"] for o in outs], axis=1)
        valid = np.stack([o["valid"] for o in outs], axis=1)
        flat = vals.reshape(-1)
        fvalid = valid.reshape(-1)
        mask = None if fvalid.all() else ~fvalid
        if is_decimal:
            shift = _static_decimal_shift(spec0, pa_type)
            if shift is None:
                return None
            mantissa = flat.astype(np.int64, copy=False) * 10 ** shift
            return _decimal128_from_mantissa(
                mantissa, fvalid, pa_type)
        return pa.array(
            flat.astype(_numpy_dtype_for(pa_type), copy=False), mask=mask)

    def _flat_struct_values(self, group: Group, slot_path, max_size: int,
                            compact_mask=None, compact_rows=None):
        """Record-major flat StructArray over all OCCURS slots of a group
        element whose fields are all numeric leaves (exp3's
        STRATEGY-DETAIL). None -> per-slot path."""
        pa = _pa()
        names, children = [], []
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group) or child.is_array:
                return None
            flat = self._flat_slot_values(child, slot_path, max_size,
                                          compact_mask=compact_mask,
                                          compact_rows=compact_rows)
            if flat is None:
                return None
            names.append(child.name)
            children.append(flat)
        if not children:
            return None
        return pa.StructArray.from_arrays(children, names=names)

    def _list_array(self, st: Statement, slot_path):
        """OCCURS -> ListArray: element slots interleaved via one take."""
        fc = self.fc
        if fc is None:
            return self._list_array_impl(st, slot_path)
        tok = fc.begin()
        arr = self._list_array_impl(st, slot_path)
        # list glue (offsets, interleave take) charged to the array
        # field itself; element builds are nested regions with their own
        # charges — the OCCURS slots share the statement name, so the
        # whole array still reads as one cost row
        cols = self.decoder.plan.columns_for(st)
        name = self.decoder.plan.cost_name(cols[0]) if cols else st.name
        fc.commit(tok, (name,), fieldcost.PLANE_ASSEMBLE, 0, 0)
        return arr

    def _subtree_planned(self, st: Statement) -> bool:
        """True when any leaf under `st` has a compiled column. False
        means the whole subtree was pruned by the projection and its
        output is pure nulls — buildable without walking slots."""
        planned = getattr(self.decoder, "planned_statement_ids", None)
        if planned is None:
            return True
        if id(st) in planned:
            return True
        if isinstance(st, Group):
            return any(self._subtree_planned(c) for c in st.children)
        return False

    def _flat_null_values(self, st: Statement, max_size: int):
        """Record-major all-null values array for a PRUNED constant-size
        OCCURS subtree (primitive elements, or a group of primitive
        non-array children) — shape-identical to what the per-slot walk
        would build (valid structs, null leaves), at O(fields) cost
        instead of O(slots). None -> caller takes the slow exact path."""
        pa = _pa()
        total = self.n * max_size
        if not isinstance(st, Group):
            return pa.nulls(total,
                            type=to_arrow_type(primitive_data_type(st)))
        names, children = [], []
        for child in st.children:
            if child.is_filler:
                continue
            if isinstance(child, Group) or child.is_array:
                return None
            names.append(child.name)
            children.append(pa.nulls(
                total, type=to_arrow_type(primitive_data_type(child))))
        if not children:
            return None
        return pa.StructArray.from_arrays(children, names=names)

    def _compact_visibility(self, st: Statement):
        """Row mask under which `st` (a decode-once OCCURS subtree) is
        visible, IF the hidden rows are guaranteed nulled at an enclosing
        segment-redefine struct: there, child buffers are logically
        invisible, so values need building only for the visible rows
        (exp3: the 2000-slot STRATEGY plane shrinks from every record to
        just the C records). None = no mask, or no null-struct
        guarantee — callers must then build positionally."""
        if self.redefine_masks is None:
            return None
        node, redef = st.parent, None
        while node is not None:
            if getattr(node, "is_segment_redefine", False):
                redef = node
                break
            node = node.parent
        # the redefine root only receives its struct null mask when it is
        # built as a row-level struct: itself not an array, and not
        # nested inside one (element structs are built unmasked)
        if redef is None or redef.is_array:
            return None
        p = redef.parent
        while p is not None:
            if p.is_array:
                return None
            p = p.parent
        mask = self.redefine_masks.get(redef.name.upper())
        if mask is None or bool(mask.all()):
            return None  # fully visible: the positional path IS compact
        return mask

    def _list_array_impl(self, st: Statement, slot_path):
        pa = _pa()
        n, max_size = self.n, st.array_max_size
        counts_probe = self._occurs_counts(st)
        if n and max_size and n * max_size < 2**31 - 1:
            # position-addressed assembly: ONE flat record-major values
            # array (slot s of record i at i*S+s), built natively when
            # the fused kernel applies and by the numpy stack path
            # otherwise — never the slot-major concat + random-access
            # take interleave below
            flat = None
            if not self._subtree_planned(st):
                # projection pruned the whole plane: zero assembly —
                # the pushdown claim that an unselected wide OCCURS
                # (exp3's 2000-element STRATEGY) costs nothing
                flat = self._flat_null_values(st, max_size)
            if flat is None and counts_probe is None:
                # decode-once + segment mask: values for visible rows
                # only; hidden rows get EMPTY lists, invisible under
                # their null redefine struct (Arrow equality and every
                # consumer read nulls logically)
                cmask = self._compact_visibility(st)
                if cmask is not None:
                    rows = np.nonzero(cmask)[0]
                    cflat = (self._flat_struct_values(
                                 st, slot_path, max_size,
                                 compact_mask=cmask, compact_rows=rows)
                             if isinstance(st, Group)
                             else self._flat_slot_values(
                                 st, slot_path, max_size,
                                 compact_mask=cmask, compact_rows=rows))
                    if cflat is not None:
                        offsets = np.zeros(n + 1, dtype=np.int32)
                        np.cumsum(np.where(cmask, max_size, 0),
                                  out=offsets[1:])
                        return pa.ListArray.from_arrays(pa.array(offsets),
                                                        cflat)
            if flat is None:
                flat = (self._flat_struct_values(st, slot_path, max_size)
                        if isinstance(st, Group)
                        else self._flat_slot_values(st, slot_path,
                                                    max_size))
            if flat is not None:
                if counts_probe is None:
                    # constant-size OCCURS: uniform offsets, zero copies
                    offsets = np.arange(n + 1, dtype=np.int32) * max_size
                    return pa.ListArray.from_arrays(pa.array(offsets),
                                                    flat)
                # DEPENDING ON: drop the unused tail slots with one
                # ASCENDING-index gather over the record-major array (a
                # sequential copy, not the interleave the slot-major
                # shape forced); no gather at all when every record is
                # full
                counts = counts_probe
                mask = np.arange(max_size)[None, :] < counts[:, None]
                if bool(mask.all()):
                    values = flat
                else:
                    indices = (np.arange(n, dtype=np.int64)[:, None]
                               * max_size
                               + np.arange(max_size,
                                           dtype=np.int64)[None, :])[mask]
                    values = flat.take(pa.array(indices))
                offsets = np.zeros(n + 1, dtype=np.int32)
                np.cumsum(counts, out=offsets[1:])
                return pa.ListArray.from_arrays(pa.array(offsets), values)
        elems = [self._statement_array(st, slot_path + (k,), as_element=True)
                 for k in range(max_size)]
        counts = counts_probe
        if n == 0 or max_size == 0:
            value_type = (elems[0].type if elems
                          else to_arrow_type(self._element_schema_type(st)))
            return pa.ListArray.from_arrays(
                pa.array(np.zeros(n + 1, dtype=np.int32)),
                pa.nulls(0, type=value_type))
        # element k of record i sits at position k*n + i of the concatenation
        idx = (np.arange(max_size)[None, :] * n
               + np.arange(n)[:, None])
        if counts is None:
            lengths = np.full(n, max_size, dtype=np.int64)
            indices = idx.ravel()
        else:
            mask = np.arange(max_size)[None, :] < counts[:, None]
            lengths = counts
            indices = idx[mask]
        values = pa.concat_arrays(elems).take(indices)
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lengths, out=offsets[1:])
        return pa.ListArray.from_arrays(pa.array(offsets), values)

    def _element_schema_type(self, st: Statement):
        if isinstance(st, Group):
            return StructType(self._group_fields(st))
        return primitive_data_type(st)

    def _group_fields(self, group: Group) -> List[Field]:
        fields = []
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group):
                if child.parent_segment is not None:
                    continue
                t = self._element_schema_type(child)
                fields.append(Field(
                    child.name, ArrayType(t) if child.is_array else t))
            else:
                t = primitive_data_type(child)
                fields.append(Field(
                    child.name, ArrayType(t) if child.is_array else t))
        return fields

    def _struct_array(self, group: Group, slot_path, null_mask=None):
        pa = _pa()
        names, children = [], []
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group) and child.parent_segment is not None:
                continue  # hierarchical child segments never reach this path
            names.append(child.name)
            children.append(self._statement_array(child, slot_path))
        if not children:
            return pa.nulls(self.n, type=pa.struct([]))
        return pa.StructArray.from_arrays(
            children, names=names,
            mask=None if null_mask is None else pa.array(null_mask))

    def _statement_array(self, st: Statement, slot_path,
                         as_element: bool = False):
        pa = _pa()
        if st.is_array and not as_element:
            return self._list_array(st, slot_path)
        if isinstance(st, Group):
            if st.is_segment_redefine and not as_element:
                if self.redefine_masks is not None:
                    mask = self.redefine_masks.get(st.name.upper())
                    if mask is None or not mask.any():
                        t = to_arrow_type(StructType(self._group_fields(st)))
                        return pa.nulls(self.n, type=t)
                    return self._struct_array(st, slot_path,
                                              null_mask=~mask)
                if (self.active is None
                        or st.name.upper() != self.active.upper()):
                    t = to_arrow_type(StructType(self._group_fields(st)))
                    return pa.nulls(self.n, type=t)
            return self._struct_array(st, slot_path)
        return self._leaf_array(st, slot_path)

    # -- top level ---------------------------------------------------------

    def body_columns(self, policy: SchemaRetentionPolicy):
        """(name, array) pairs for the record body, matching
        CobolOutputSchema._create_schema ordering."""
        out = []
        for root in self.decoder.copybook.ast.children:
            if not isinstance(root, Group):
                continue
            if policy is SchemaRetentionPolicy.COLLAPSE_ROOT:
                for child in root.children:
                    if child.is_filler:
                        continue
                    if isinstance(child, Group) and child.parent_segment is not None:
                        continue
                    out.append((child.name, self._statement_array(child, ())))
            else:
                out.append((root.name, self._statement_array(root, ())))
        return out


def segment_table(batch: DecodedBatch,
                  active: Optional[str],
                  output_schema,
                  file_id: int,
                  record_ids: Optional[np.ndarray],
                  seg_level_ids: Optional[Sequence[Sequence[object]]],
                  input_file_name: str = "",
                  redefine_masks: Optional[dict] = None,
                  corrupt_reasons: Optional[Sequence] = None):
    """One Arrow table for one decoded batch (single active segment, or a
    decode-once batch with per-row redefine masks), with generated columns
    prepended per the output schema. `corrupt_reasons`: per-row values of
    the trailing corrupt-record debug column (None entries = clean)."""
    pa = _pa()
    builder = ArrowBatchBuilder(batch, active, redefine_masks)
    n = batch.n_records
    schema = output_schema.schema

    def seg_arrays():
        from .result import SegLevelColumns

        out = []
        for lvl in range(output_schema.generate_seg_id_field_count):
            if isinstance(seg_level_ids, SegLevelColumns):
                ab = seg_level_ids.arrow_level(lvl)
                if ab is not None:
                    # native int-formatted Seg_Id buffers — no Python
                    # strings at all
                    offsets, data, valid = ab
                    vbuf = (None if valid.all()
                            else _validity_buffer(valid))
                    out.append(pa.Array.from_buffers(
                        pa.string(), n,
                        [vbuf, pa.py_buffer(offsets),
                         pa.py_buffer(data)]))
                    continue
                # per-level object column straight into Arrow (no
                # per-row list materialization)
                vals = (seg_level_ids.levels[lvl]
                        if lvl < len(seg_level_ids.levels)
                        else [None] * n)
            elif seg_level_ids is not None:
                vals = [row[lvl] if row is not None and lvl < len(row)
                        else None for row in seg_level_ids]
            else:
                vals = [None] * n
            out.append(pa.array(vals, type=pa.string()))
        return out

    # Generated columns in ROW order (extractors._apply_post_processing /
    # reference RecordExtractors.applyRecordPostProcessing): with record
    # ids the file name goes before the Seg_Id levels; without, after.
    # The declared schema prepends the file-name field before the Seg_Id
    # fields in BOTH cases (CobolSchema.scala:99-103) — the reference binds
    # Spark Rows positionally, so that (reference) misalignment is parity;
    # columns here are therefore labeled positionally, exactly like rows.
    def file_name_col():
        # constant string column straight into Arrow buffers (native
        # memcpy fill) — never n Python string objects
        bufs = native.const_string_col(n, input_file_name)
        if bufs is not None:
            offsets, data = bufs
            return pa.Array.from_buffers(
                pa.string(), n,
                [None, pa.py_buffer(offsets), pa.py_buffer(data)])
        return pa.array([input_file_name] * n, type=pa.string())

    cols: List[object] = []
    if output_schema.generate_record_id:
        cols.append(pa.array(np.full(n, file_id, dtype=np.int32)))
        rids = (np.asarray(record_ids, dtype=np.int64) if record_ids is not None
                else np.arange(n, dtype=np.int64))
        cols.append(pa.array(rids))
        if output_schema.input_file_name_field:
            cols.append(file_name_col())
        cols.extend(seg_arrays())
    else:
        cols.extend(seg_arrays())
        if output_schema.input_file_name_field:
            cols.append(file_name_col())
    cols.extend(arr for _, arr in builder.body_columns(output_schema.policy))
    if getattr(output_schema, "corrupt_record_field", ""):
        cols.append(pa.nulls(n, pa.string()) if corrupt_reasons is None
                    else pa.array(list(corrupt_reasons), type=pa.string()))
    target = arrow_schema(schema)
    if len(cols) != len(target):
        raise ValueError(
            f"Arrow column count mismatch: built {len(cols)}, "
            f"schema {len(target)}")
    arrays = [c.cast(target.field(i).type)
              if c.type != target.field(i).type else c
              for i, c in enumerate(cols)]
    return pa.Table.from_arrays(arrays, schema=target)


def rows_to_table(rows: List[List[object]], struct: StructType):
    """Fallback: build a typed table from materialized Python rows (host
    backend, hierarchical assemblies). Same declared types as the fast
    path, so both produce schema-identical tables."""
    pa = _pa()
    target = arrow_schema(struct)
    arrays = []
    for i, f in enumerate(struct.fields):
        col = [row[i] for row in rows]
        arrays.append(pa.array(_normalize_objects(col, f.dtype),
                               type=target.field(i).type))
    return pa.Table.from_arrays(arrays, schema=target)


def _normalize_objects(values, dtype):
    """Tuples (group values) -> dicts keyed by field name so pa.array can
    build struct arrays from the nested row shape."""
    if isinstance(dtype, StructType):
        names = [f.name for f in dtype.fields]
        return [None if v is None else
                {nm: nv for nm, nv in zip(
                    names, (_normalize_objects([x], f.dtype)[0]
                            for x, f in zip(v, dtype.fields)))}
                for v in values]
    if isinstance(dtype, ArrayType):
        return [None if v is None else _normalize_objects(list(v), dtype.element)
                for v in values]
    return list(values)
