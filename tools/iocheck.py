"""Remote-IO smoke check: fsspec scan -> cache warm-up -> parity diff.

Drives the remote-storage subsystem (cobrix_tpu.io) end to end against
an in-memory fsspec filesystem on the two bench profiles — exp1
fixed-length and exp2 RDW multisegment:

  1. remote (`memory://`) scan vs local-file scan of the same bytes:
     rows + Arrow must be identical;
  2. cold scan with `cache_dir=` -> warm scan: the warm read must fetch
     ZERO backend bytes, and a VRL warm read must also skip the
     sequential index pass (sparse-index store hit);
  3. a changed remote object must invalidate both cache planes;
  4. a flaky backend (injected transient faults) must retry to a clean,
     identical result with the retries on the ledger.

    python tools/iocheck.py                 # quick: ~4 MB per profile
    python tools/iocheck.py --mb 32         # bigger inputs
    python tools/iocheck.py --sweep         # prefetch x block-size grid
                                            # (slow; tier-1 runs quick)

Exit code 0 = all parity + cache-plane checks hold; 1 = any mismatch.
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _profiles(mb: float):
    from cobrix_tpu.testing.generators import (
        EXP1_COPYBOOK,
        EXP2_COPYBOOK,
        generate_exp1,
        generate_exp2,
    )

    n1 = max(64, int(mb * 1024 * 1024) // 1493)
    n2 = max(1000, int(mb * 1024 * 1024 / 66))
    return [
        ("exp1_fixed", generate_exp1(n1, seed=7).tobytes(),
         dict(copybook_contents=EXP1_COPYBOOK), False),
        ("exp2_rdw", generate_exp2(n2, seed=7),
         dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence="true",
              segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P",
              input_split_size_mb="1",
              segment_id_prefix="IO"), True),
    ]


def _mem_url(data: bytes) -> str:
    import fsspec

    bucket = f"/iocheck-{uuid.uuid4().hex[:10]}"
    fs = fsspec.filesystem("memory")
    with fs.open(f"{bucket}/data.dat", "wb") as f:
        f.write(data)
    return f"memory:/{bucket}/data.dat"


def _io(result) -> dict:
    return result.metrics.as_dict().get("io") or {}


def check_profile(name: str, data: bytes, kw: dict, is_vrl: bool,
                  prefetch: str, block_mb: str) -> bool:
    from cobrix_tpu import read_cobol

    mb = len(data) / (1024 * 1024)
    url = _mem_url(data)
    cache = tempfile.mkdtemp(prefix="iocheck-cache-")
    path = None
    ok = True

    def fail(msg: str) -> None:
        nonlocal ok
        ok = False
        print(f"{'':<12} FAILED: {msg}")

    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(data)
            path = f.name
        io_kw = dict(kw, prefetch_blocks=prefetch, io_block_mb=block_mb)
        local = read_cobol(path, **kw)

        t0 = time.perf_counter()
        remote = read_cobol(url, **io_kw)
        remote_s = time.perf_counter() - t0
        if not remote.to_arrow().equals(local.to_arrow()):
            fail("remote scan diverged from local scan")

        cache_kw = dict(io_kw, cache_dir=cache)
        cold = read_cobol(url, **cache_kw)
        t0 = time.perf_counter()
        warm = read_cobol(url, **cache_kw)
        warm_s = time.perf_counter() - t0
        cold_io, warm_io = _io(cold), _io(warm)
        if not warm.to_arrow().equals(local.to_arrow()):
            fail("warm cached scan diverged")
        if warm_io.get("bytes_fetched", -1) != 0:
            fail(f"warm scan fetched {warm_io.get('bytes_fetched')} "
                 "backend bytes (expected 0)")
        if is_vrl and (warm_io.get("index_hits", 0) < 1
                       or warm_io.get("index_misses", 0) != 0):
            fail(f"warm VRL scan re-indexed: {warm_io}")

        # changed object invalidates both planes
        import fsspec

        half = len(data) // 2
        with fsspec.filesystem("memory").open(
                url[len("memory://"):], "wb") as f:
            f.write(data[:half])
        changed = read_cobol(url, **dict(
            cache_kw, record_error_policy="permissive"))
        ch_io = _io(changed)
        if ch_io.get("bytes_fetched", 0) <= 0:
            fail("changed object served stale cached bytes")
        if is_vrl and ch_io.get("index_hits", 0) != 0:
            fail("changed object served a stale sparse index")

        # flaky backend: transient faults retry to an identical result
        from cobrix_tpu.testing.faults import register_chaos_backend

        scheme = f"ioq{uuid.uuid4().hex[:8]}"
        register_chaos_backend(scheme, data, fail_reads=2)
        flaky = read_cobol(f"{scheme}://data.dat", **dict(
            io_kw, io_retry_attempts="5", io_retry_base_delay_ms="1"))
        if not flaky.to_arrow().equals(local.to_arrow()):
            fail("flaky-backend scan diverged after retries")
        if (flaky.diagnostics is None
                or flaky.diagnostics.io_retries < 2):
            fail("flaky-backend retries missing from the ledger")

        util = warm_io.get("prefetch_utilization", cold_io.get(
            "prefetch_utilization", 0.0))
        print(f"{name:<12} {mb:7.1f} MB | remote {mb / remote_s:7.1f} MB/s"
              f" | warm {mb / warm_s:7.1f} MB/s | "
              f"fetched {cold_io.get('bytes_fetched', 0) / 1e6:.1f} MB"
              f" -> 0 MB | prefetch util {util:.2f}")
        planes = (f"block {warm_io.get('block_hits', 0)} hit / "
                  f"index {warm_io.get('index_hits', 0)} hit"
                  if warm_io else "io layer off")
        print(f"{'':<12} warm planes: {planes} | "
              f"retries ledgered: {flaky.diagnostics.io_retries}")
        return ok
    finally:
        if path:
            os.unlink(path)
        shutil.rmtree(cache, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=float, default=4.0,
                    help="approx input size per profile (MB)")
    ap.add_argument("--prefetch", default="2",
                    help="prefetch_blocks for the remote reads")
    ap.add_argument("--block-mb", default="0.5",
                    help="io_block_mb cache/read-ahead granularity")
    ap.add_argument("--sweep", action="store_true",
                    help="run a prefetch x block-size grid (slow)")
    args = ap.parse_args()

    try:
        import fsspec  # noqa: F401
    except ImportError:
        print("SKIP: fsspec is not installed (the remote-io subsystem "
              "is optional; pip install fsspec)")
        return 0

    ok = True
    for name, data, kw, is_vrl in _profiles(args.mb):
        if args.sweep:
            for p in ("0", "1", "4"):
                for b in ("0.1", args.block_mb, "2.0"):
                    print(f"--- {name} prefetch={p} io_block_mb={b}")
                    ok &= check_profile(name, data, kw, is_vrl, p, b)
        else:
            ok &= check_profile(name, data, kw, is_vrl,
                                args.prefetch, args.block_mb)
    print("OK: remote scans identical, cache planes verified" if ok
          else "FAILED: remote-io checks diverged")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
