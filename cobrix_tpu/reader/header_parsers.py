"""Record header parsers: derive each record's length from its header.

Mirrors the reference pluggable trait (headerparsers/RecordHeaderParser.scala:34-76)
and its RDW (RecordHeaderParserRDW.scala:24-87) and fixed-length
(RecordHeaderParserFixedLen.scala:22-52) implementations, plus the
dotted-name factory for custom parsers (RecordHeaderParserFactory.scala:22-45).
"""
from __future__ import annotations

import importlib
from dataclasses import dataclass

from ..copybook.datatypes import MAX_RDW_RECORD_SIZE
from .diagnostics import FramingError, hex_snapshot


@dataclass(frozen=True)
class RecordMetadata:
    record_length: int
    is_valid: bool


class RecordHeaderParser:
    """Pluggable record-length-from-header contract."""

    @property
    def header_length(self) -> int:
        raise NotImplementedError

    @property
    def is_header_defined_in_copybook(self) -> bool:
        return False

    def get_record_metadata(self, header: bytes, file_offset: int,
                            file_size: int, record_num: int) -> RecordMetadata:
        raise NotImplementedError

    def on_receive_additional_info(self, additional_info: str) -> None:
        pass


class RdwHeaderParser(RecordHeaderParser):
    """4-byte RDW: BE length in bytes[0..1], LE in bytes[3..2], plus an
    adjustment; zero-length records are a hard error; file header/footer
    regions are emitted as invalid records so callers skip them."""

    def __init__(self, is_big_endian: bool = False, file_header_bytes: int = 0,
                 file_footer_bytes: int = 0, rdw_adjustment: int = 0):
        self.is_big_endian = is_big_endian
        self.file_header_bytes = file_header_bytes
        self.file_footer_bytes = file_footer_bytes
        self.rdw_adjustment = rdw_adjustment

    @property
    def header_length(self) -> int:
        return 4

    def get_record_metadata(self, header: bytes, file_offset: int,
                            file_size: int, record_num: int) -> RecordMetadata:
        hlen = self.header_length
        if self.file_header_bytes > hlen and file_offset == hlen:
            return RecordMetadata(self.file_header_bytes - hlen, False)
        if (file_size > 0 and self.file_footer_bytes > 0
                and file_size - file_offset <= self.file_footer_bytes):
            return RecordMetadata(file_size - file_offset, False)
        if len(header) < hlen:
            return RecordMetadata(-1, False)
        if self.is_big_endian:
            length = header[1] + 256 * header[0] + self.rdw_adjustment
        else:
            length = header[2] + 256 * header[3] + self.rdw_adjustment
        if length > 0:
            if length > MAX_RDW_RECORD_SIZE:
                hdr = ",".join(str(b) for b in header)
                raise FramingError(
                    f"RDW headers too big (length = {length} > "
                    f"{MAX_RDW_RECORD_SIZE}). Headers = {hdr} at file offset "
                    f"{file_offset} (header bytes: {hex_snapshot(header)}).",
                    offset=file_offset, reason="oversized RDW header",
                    header=header)
            return RecordMetadata(length, True)
        hdr = ",".join(str(b) for b in header)
        raise FramingError(
            f"RDW headers should never be zero ({hdr}). "
            f"Found zero size record at file offset {file_offset} "
            f"(header bytes: {hex_snapshot(header)}).",
            offset=file_offset, reason="zero-length RDW header",
            header=header)


class FixedLengthHeaderParser(RecordHeaderParser):
    """No header; records are fixed-size; optional file header/footer bytes
    are emitted as invalid records."""

    def __init__(self, record_size: int, file_header_bytes: int = 0,
                 file_footer_bytes: int = 0):
        self.record_size = record_size
        self.file_header_bytes = file_header_bytes
        self.file_footer_bytes = file_footer_bytes

    @property
    def header_length(self) -> int:
        return 0

    def get_record_metadata(self, header: bytes, file_offset: int,
                            file_size: int, record_num: int) -> RecordMetadata:
        if self.file_header_bytes > 0 and file_offset == 0:
            return RecordMetadata(self.file_header_bytes, False)
        if (file_size > 0 and self.file_footer_bytes > 0
                and file_size - file_offset <= self.file_footer_bytes):
            return RecordMetadata(file_size - file_offset, False)
        if file_size - file_offset >= self.record_size:
            return RecordMetadata(self.record_size, True)
        return RecordMetadata(-1, False)


def create_record_header_parser(name: str,
                                record_size: int = 0,
                                file_header_bytes: int = 0,
                                file_footer_bytes: int = 0,
                                rdw_adjustment: int = 0) -> RecordHeaderParser:
    """Create a parser by well-known name ('rdw', 'rdw_big_endian',
    'rdw_little_endian', 'fixed_length') or by a dotted Python path to a
    custom RecordHeaderParser class."""
    lowered = name.lower()
    if lowered in ("rdw", "rdw_little_endian"):
        return RdwHeaderParser(False, file_header_bytes, file_footer_bytes,
                               rdw_adjustment)
    if lowered == "rdw_big_endian":
        return RdwHeaderParser(True, file_header_bytes, file_footer_bytes,
                               rdw_adjustment)
    if lowered in ("fixed_length", "fixed_len"):
        return FixedLengthHeaderParser(record_size, file_header_bytes,
                                       file_footer_bytes)
    module_name, _, class_name = name.rpartition(".")
    if not module_name:
        raise ValueError(
            f"Unknown record header parser '{name}'. Use one of 'rdw', "
            "'rdw_big_endian', 'rdw_little_endian', 'fixed_length', or a "
            "dotted path to a RecordHeaderParser subclass "
            "(e.g. 'my_pkg.my_module.MyParser').")
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        raise ValueError(
            f"Custom record header parser '{name}': module "
            f"'{module_name}' could not be imported ({exc}).") from exc
    try:
        cls = getattr(module, class_name)
    except AttributeError:
        raise ValueError(
            f"Custom record header parser '{name}': module "
            f"'{module_name}' has no attribute '{class_name}'.") from None
    instance = cls()
    if not isinstance(instance, RecordHeaderParser):
        raise TypeError(
            f"Custom record header parser {name} must subclass RecordHeaderParser")
    return instance
