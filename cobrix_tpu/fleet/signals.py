"""Autoscaling signals derived from the federated fleet view.

`derive_signals` turns one `FleetView` (plus the federator's scrape
history and SLO rollup) into a **recommendation record** — the input a
horizontal autoscaler or a human reads. It recommends; it does not
actuate: nothing here starts or stops replicas, drains traffic, or
rebalances caches. ``desired_replicas`` means "with the load observed
over the fast window, this many replicas would keep queue waits under
target and stop the error budget from burning" — no more.

Scale-UP evidence (any one suffices; all are listed in ``reasons``):

* **queue pressure** — the fleet-wide admission queue-wait quantile
  over the fast window exceeds ``queue_wait_target_s`` (new work is
  waiting for slots that more replicas would provide);
* **rejections** — admission refused work in the window
  (``queue_full`` / ``queue_timeout`` / ``overloaded``): demand
  already exceeded what queueing could absorb;
* **SLO burn** — an objective burns over BOTH windows (the classic
  multi-window alert shape: fast alone is a blip, slow alone is old
  news, both together is a real regression in progress);
* **memory pressure** — replicas at their degrade/shed watermark
  (more replicas spread the RSS).

Scale-DOWN needs ALL of: low slot utilization, idle queue, no burning
objective, no rejections — and steps down one replica at a time.

Cache-affinity hints ride along: the hottest plan/file fingerprints
per replica (from the heartbeat heat top-K), shaped for the
consistent-hash routing front of ROADMAP item 5 — "requests matching
this fingerprint are warm HERE".
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple

# the admission-queue histogram the queue-pressure signal reads, and
# the rejection counter — public: the federator prunes its scrape
# HISTORY down to exactly these families (signals is the only consumer
# of historical snapshots, and whole parsed expositions held for 15
# minutes would pin real memory on every federating replica)
QUEUE_WAIT_METRIC = "cobrix_serve_queue_wait_seconds"
REJECT_METRIC = "cobrix_serve_scans_rejected_total"
HISTORY_FAMILIES = (QUEUE_WAIT_METRIC, REJECT_METRIC)
# rejection reasons that are demand signals (a client-side protocol
# refusal is not evidence the fleet is too small)
_PRESSURE_REASONS = ("queue_full", "queue_timeout", "overloaded")


def _cluster_histogram(view, name: str) -> Optional[dict]:
    """Sum one histogram family across reachable replicas (all label
    sets folded): {buckets: [(bound, cum)], count, sum}. The le-bound
    folding itself lives in obs.promparse (one owner)."""
    from ..obs.promparse import fold_histogram

    acc = None
    for scrape in view.reachable():
        fam = scrape.families.get(name)
        if fam is not None:
            acc = fold_histogram(fam, acc)
    if acc is None:
        return None
    return {"buckets": sorted(acc["buckets"].items()),
            "count": acc["count"], "sum": acc["sum"]}


def _histogram_delta(cur: Optional[dict],
                     base: Optional[dict]) -> Optional[dict]:
    """Windowed histogram = current cumulative minus the window-base
    snapshot. No baseline -> None: lifetime totals must not masquerade
    as recent activity (a freshly-started federator looking at a
    week-old fleet would otherwise read history as a present emergency
    and recommend scale-up off stale evidence)."""
    if cur is None or base is None:
        return None
    base_buckets = dict(base["buckets"])
    buckets = [(b, max(0.0, c - base_buckets.get(b, 0.0)))
               for b, c in cur["buckets"]]
    return {"buckets": buckets,
            "count": max(0.0, cur["count"] - base["count"]),
            "sum": max(0.0, cur["sum"] - base["sum"])}


def _bucket_quantile(hist: Optional[dict],
                     q: float) -> Optional[float]:
    """Approximate quantile (upper bucket bound), like
    obs.metrics.Histogram.quantile; None on an empty window."""
    if hist is None or hist["count"] <= 0:
        return None
    target = q * hist["count"]
    finite = [b for b, _ in hist["buckets"]
              if b != float("inf")]
    prev_cum = 0.0
    for bound, cum in hist["buckets"]:
        if cum >= target and cum > prev_cum:
            if bound == float("inf"):
                return finite[-1] if finite else None
            return bound
        prev_cum = cum
    return finite[-1] if finite else None


def _counter_total(view, name: str,
                   label_filter: Optional[dict] = None,
                   label_in: Optional[Tuple[str, tuple]] = None
                   ) -> float:
    total = 0.0
    for scrape in view.reachable():
        fam = scrape.families.get(name)
        if fam is None:
            continue
        for s in fam.samples:
            labels = dict(s.labels)
            if label_filter and any(labels.get(k) != v
                                    for k, v in label_filter.items()):
                continue
            if label_in and labels.get(label_in[0]) not in label_in[1]:
                continue
            total += s.value
    return total


def _window_base(history, window_s: float):
    """The delta baseline as ``(view, age_s)``: the oldest snapshot
    inside the window, else the NEWEST one older than it — a consumer
    polling at a cadence >= window_s (a standard 60s+ autoscaler loop)
    must still get a baseline, or the rate signals would be permanently
    inert exactly for the callers they exist for. The observed span is
    reported so readers see when the window is wider than asked.
    None only when no prior snapshot exists at all."""
    if len(history) < 2:
        return None
    now = history[-1][0]
    horizon = now - window_s
    inside = [(ts, v) for ts, v in history[:-1] if ts >= horizon]
    if inside:
        ts, view = inside[0]
    else:
        ts, view = history[-2]  # newest prior snapshot, outside
    return view, max(0.0, now - ts)


def derive_signals(view, history=None, slo_rollup: Optional[dict] = None,
                   queue_wait_target_s: float = 0.5,
                   fast_window_s: float = 60.0,
                   min_replicas: int = 1,
                   max_replicas: int = 64,
                   scale_down_utilization: float = 0.25,
                   heat_top_k: int = 8) -> dict:
    """The recommendation record (see module docstring for semantics)."""
    live = [r for r in view.replicas if r.status.state == "live"]
    n_live = len(live)
    records = [r.status.record for r in view.replicas]
    capacity = sum(r.max_concurrent_scans for r in records)
    active = sum(r.active_scans for r in records)
    queued = sum(r.queued_scans for r in records)
    utilization = (active / capacity) if capacity else None
    pressured = [r.replica_id for r in view.replicas
                 if r.status.record.pressure in ("degraded", "shed")]
    draining = [r.replica_id for r in view.replicas
                if r.status.record.draining]

    based = _window_base(history or [], fast_window_s)
    base, window_observed_s = based if based else (None, None)
    queue_cur = _cluster_histogram(view, QUEUE_WAIT_METRIC)
    queue_base = (_cluster_histogram(base, QUEUE_WAIT_METRIC)
                  if base is not None else None)
    queue_window = _histogram_delta(queue_cur, queue_base)
    queue_p90 = _bucket_quantile(queue_window, 0.90)
    queue_p50 = _bucket_quantile(queue_window, 0.50)

    if base is not None:
        rejections_window = max(0.0, _counter_total(
            view, REJECT_METRIC,
            label_in=("reason", _PRESSURE_REASONS)) - _counter_total(
            base, REJECT_METRIC,
            label_in=("reason", _PRESSURE_REASONS)))
    else:
        # same no-baseline honesty as the histogram delta: cumulative
        # lifetime rejections are not evidence of pressure NOW
        rejections_window = 0.0

    burning_both = []
    if slo_rollup:
        for name, agg in (slo_rollup.get("slo") or {}).items():
            fast = (agg.get("burn_fast") or {}).get("burn")
            slow = (agg.get("burn_slow") or {}).get("burn")
            if fast is not None and slow is not None \
                    and fast > 1.0 and slow > 1.0:
                burning_both.append(name)

    reasons: List[str] = []
    desired = max(1, n_live)
    scale_up = False
    if queue_p90 is not None and queue_p90 > queue_wait_target_s:
        scale_up = True
        # waits scale roughly with queue length per slot: grow by half
        # the fleet, at least one replica
        desired = max(desired, n_live + max(1, math.ceil(n_live / 2)))
        reasons.append(
            f"queue_wait p90 {queue_p90:.3g}s over the "
            f"{queue_wait_target_s:.3g}s target in the last "
            f"{fast_window_s:.0f}s")
    if rejections_window > 0:
        scale_up = True
        desired = max(desired, n_live + 1)
        reasons.append(
            f"{rejections_window:.0f} admission rejection(s) "
            f"({'/'.join(_PRESSURE_REASONS)}) in the window")
    if burning_both:
        scale_up = True
        desired = max(desired, n_live + max(1, math.ceil(n_live / 2)))
        reasons.append("SLO burn over both windows: "
                       + ", ".join(sorted(burning_both)))
    if pressured:
        scale_up = True
        desired = max(desired, n_live + 1)
        reasons.append("memory pressure (degraded/shed) on: "
                       + ", ".join(sorted(pressured)))
    if not scale_up:
        idle_queue = (queue_p90 is None
                      or queue_p90 <= queue_wait_target_s / 10.0)
        # scale-down needs the same evidentiary bar as scale-up: a real
        # observation window. The first scrape after a federator
        # restart must recommend the status quo, in either direction
        if (base is not None
                and utilization is not None
                and utilization < scale_down_utilization
                and queued == 0 and idle_queue
                and not burning_both and rejections_window == 0
                and n_live > min_replicas):
            desired = n_live - 1
            reasons.append(
                f"slot utilization {utilization:.0%} under "
                f"{scale_down_utilization:.0%} with an idle queue")
        else:
            reasons.append("steady: no scale signal in the window")
    desired = max(min_replicas, min(max_replicas, desired))

    # cache-affinity hints: hottest fingerprint -> the replica where it
    # is hottest (route-for-warmth, the item-5 routing front's input)
    heat_by_key: Dict[str, Tuple[str, int, int]] = {}
    for r in view.replicas:
        for entry in r.status.record.heat:
            key = entry.get("key")
            count = int(entry.get("count") or 0)
            if not key:
                continue
            best = heat_by_key.get(key)
            total = (best[2] if best else 0) + count
            if best is None or count > best[1]:
                heat_by_key[key] = (r.replica_id, count, total)
            else:
                heat_by_key[key] = (best[0], best[1], total)
    affinity = [
        {"key": key, "replica": rid, "count": count, "fleet_count": tot}
        for key, (rid, count, tot) in sorted(
            heat_by_key.items(), key=lambda kv: -kv[1][2])
    ][:max(0, heat_top_k)]

    return {
        "generated_at": time.time(),
        "desired_replicas": desired,
        "live_replicas": n_live,
        "known_replicas": len(view.replicas),
        "reasons": reasons,
        "inputs": {
            "window_s": fast_window_s,
            "window_has_baseline": base is not None,
            # actual span covered by the baseline delta — wider than
            # window_s when the caller polls slower than the window
            "window_observed_s": (round(window_observed_s, 1)
                                  if window_observed_s is not None
                                  else None),
            "queue_wait_p50_s": queue_p50,
            "queue_wait_p90_s": queue_p90,
            "queue_wait_target_s": queue_wait_target_s,
            "rejections_in_window": rejections_window,
            "slots_active": active,
            "slots_capacity": capacity,
            "utilization": (round(utilization, 4)
                            if utilization is not None else None),
            "queued_scans": queued,
            "slos_burning_both_windows": sorted(burning_both),
            "pressured_replicas": sorted(pressured),
            "draining_replicas": sorted(draining),
        },
        "cache_affinity": affinity,
        # honesty clause, machine-readable: consumers must treat this
        # as advice — the record never actuates anything by itself
        "actuates": False,
    }
