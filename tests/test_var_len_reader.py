"""Variable-length reader stack tests: RDW framing, multisegment filtering,
Seg_Id generation, sparse index, golden parity for test4 (multisegment
ASCII/RDW) — tier-2/3 strategy of SURVEY.md §4 without a cluster.
"""
import pytest

from cobrix_tpu.copybook.datatypes import SchemaRetentionPolicy
from cobrix_tpu.reader.header_parsers import (
    FixedLengthHeaderParser,
    RdwHeaderParser,
)
from cobrix_tpu.reader.index import sparse_index_generator
from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3
from cobrix_tpu.reader.json_out import rows_to_json
from cobrix_tpu.reader.parameters import (
    MultisegmentParameters,
    ReaderParameters,
)
from cobrix_tpu.reader.raw_extractors import RawRecordContext, TextRecordExtractor
from cobrix_tpu.reader.schema import CobolOutputSchema
from cobrix_tpu.reader.stream import MemoryStream
from cobrix_tpu.reader.var_len_reader import SegmentIdAccumulator, VarLenReader
from cobrix_tpu.testing.generators import EXP2_COPYBOOK, generate_exp2

from util import read_binary, read_copybook, read_golden_lines


class TestRdwHeaderParser:
    def test_little_endian(self):
        p = RdwHeaderParser(is_big_endian=False)
        meta = p.get_record_metadata(bytes([0, 0, 64, 1]), 0, 1000, 0)
        assert meta.record_length == 64 + 256 and meta.is_valid

    def test_big_endian(self):
        p = RdwHeaderParser(is_big_endian=True)
        meta = p.get_record_metadata(bytes([1, 64, 0, 0]), 0, 1000, 0)
        assert meta.record_length == 320 and meta.is_valid

    def test_adjustment(self):
        p = RdwHeaderParser(is_big_endian=True, rdw_adjustment=-4)
        meta = p.get_record_metadata(bytes([0, 68, 0, 0]), 0, 1000, 0)
        assert meta.record_length == 64

    def test_zero_length_raises(self):
        p = RdwHeaderParser()
        with pytest.raises(ValueError, match="never be zero"):
            p.get_record_metadata(bytes(4), 0, 1000, 0)

    def test_short_header_invalid(self):
        p = RdwHeaderParser()
        meta = p.get_record_metadata(b"\x00", 0, 1000, 0)
        assert not meta.is_valid and meta.record_length == -1


class TestTextExtractor:
    def _extract(self, payload: bytes, record_size: int = 10):
        from cobrix_tpu import parse_copybook
        cb = parse_copybook(f"       01 R.\n          05 F PIC X({record_size}).")
        ctx = RawRecordContext(0, MemoryStream(payload), cb)
        ex = TextRecordExtractor(ctx)
        out = []
        while ex.has_next():
            out.append(next(ex))
        return out

    def test_lf_records(self):
        assert self._extract(b"abc\ndef\n") == [b"abc", b"def"]

    def test_crlf_records(self):
        assert self._extract(b"abc\r\ndef\r\n") == [b"abc", b"def"]

    def test_last_record_without_eol(self):
        assert self._extract(b"abc\ndef") == [b"abc", b"def"]


class TestSegmentIdAccumulator:
    def test_root_and_child_ids(self):
        acc = SegmentIdAccumulator(["C", "P"], "ID", 0)
        acc.acquired_segment_id("C", 5)
        assert acc.get_segment_level_id(0) == "ID_0_5"
        assert acc.get_segment_level_id(1) is None
        acc.acquired_segment_id("P", 6)
        assert acc.get_segment_level_id(1) == "ID_0_5_L1_1"
        acc.acquired_segment_id("P", 7)
        assert acc.get_segment_level_id(1) == "ID_0_5_L1_2"
        acc.acquired_segment_id("C", 8)
        assert acc.get_segment_level_id(0) == "ID_0_8"
        assert acc.get_segment_level_id(1) is None


def _test4_reader():
    cob = read_copybook("test4_copybook.cob")
    params = ReaderParameters(
        is_ebcdic=False,
        is_record_sequence=True,
        generate_record_id=True,
        schema_policy=SchemaRetentionPolicy.COLLAPSE_ROOT,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT_ID",
            segment_level_ids=["C", "P"],
            segment_id_prefix="A"))
    return VarLenReader(cob, params)


class TestTest4MultisegmentGolden:
    """ASCII RDW multisegment with Seg_Id generation
    (reference Test4MultisegmentSpec; the golden is a 60-row sample)."""

    def test_host_path_matches_golden(self):
        reader = _test4_reader()
        data = read_binary("test4_data")
        rows = list(reader.iter_rows(MemoryStream(data), file_id=0,
                                     segment_id_prefix="A"))
        schema = CobolOutputSchema(
            reader.copybook, policy=SchemaRetentionPolicy.COLLAPSE_ROOT,
            generate_record_id=True, generate_seg_id_field_count=2)
        actual = rows_to_json(rows, schema.schema)
        expected = read_golden_lines("test4_expected/test4.txt")
        assert len(actual) == 1000
        assert actual[: len(expected)] == expected

    def test_columnar_path_matches_host(self):
        reader = _test4_reader()
        data = read_binary("test4_data")
        host = list(reader.iter_rows(MemoryStream(data), file_id=0,
                                     segment_id_prefix="A"))
        columnar = reader.read_rows_columnar(MemoryStream(data), file_id=0,
                                             segment_id_prefix="A")
        assert host == columnar


class TestGeneratedExp2:
    def test_host_and_columnar_agree(self):
        data = generate_exp2(300, seed=7)
        params = ReaderParameters(
            is_record_sequence=True, generate_record_id=True,
            schema_policy=SchemaRetentionPolicy.COLLAPSE_ROOT,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_level_ids=["C", "P"],
                segment_id_prefix="ID"))
        reader = VarLenReader(EXP2_COPYBOOK, params)
        host = list(reader.iter_rows(MemoryStream(data), segment_id_prefix="ID"))
        columnar = reader.read_rows_columnar(MemoryStream(data),
                                             segment_id_prefix="ID")
        assert host == columnar and len(host) == 300

    def test_segment_filter(self):
        data = generate_exp2(200, seed=3)
        params = ReaderParameters(
            is_record_sequence=True,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_id_filter=["C"]))
        reader = VarLenReader(EXP2_COPYBOOK, params)
        rows = list(reader.iter_rows(MemoryStream(data)))
        # segment-filtered row count == number of C records framed
        n_c = sum(1 for _, seg, _ in reader.frame_records(MemoryStream(data))
                  if seg == "C")
        assert len(rows) == n_c > 0


class TestSparseIndex:
    def test_split_by_record_count(self):
        data = generate_exp2(100, seed=1)
        params = ReaderParameters(is_record_sequence=True)
        reader = VarLenReader(EXP2_COPYBOOK, params)
        index = sparse_index_generator(
            0, MemoryStream(data),
            record_header_parser=reader.record_header_parser(),
            records_per_index_entry=10)
        assert len(index) >= 9
        assert index[0].offset_from == 0
        assert index[-1].offset_to == -1
        # entries chain without gaps
        for a, b in zip(index, index[1:]):
            assert a.offset_to == b.offset_from

    def test_index_shards_reproduce_full_read(self):
        data = generate_exp2(60, seed=2)
        params = ReaderParameters(is_record_sequence=True)
        reader = VarLenReader(EXP2_COPYBOOK, params)
        index = sparse_index_generator(
            0, MemoryStream(data),
            record_header_parser=reader.record_header_parser(),
            records_per_index_entry=13)
        whole = [rec for _, _, rec in reader.frame_records(MemoryStream(data))]
        sharded = []
        for entry in index:
            maximum = 0 if entry.offset_to < 0 else entry.offset_to - entry.offset_from
            stream = MemoryStream(data, start_offset=entry.offset_from,
                                  maximum_bytes=maximum)
            sharded.extend(
                rec for _, _, rec in reader.frame_records(
                    stream, start_record_id=entry.record_index,
                    starting_file_offset=entry.offset_from))
        assert sharded == whole


class TestHierarchicalColumnar:
    """The hierarchical columnar path (batched value decode + per-record
    nesting assembly) must equal the scalar extractor byte for byte and
    actually engage for standard RDW hierarchical reads."""

    def _reader(self):
        params = ReaderParameters(
            is_record_sequence=True,
            generate_record_id=True,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_id_redefine_map={"C": "STATIC_DETAILS",
                                         "P": "CONTACTS"},
                field_parent_map={"CONTACTS": "STATIC-DETAILS"}))
        return VarLenReader(EXP3_COPYBOOK, params)

    def test_matches_scalar_extractor(self):
        reader = self._reader()
        assert reader.copybook.is_hierarchical
        data = generate_exp3(150, seed=9)
        res = reader.read_result_columnar(MemoryStream(data), file_id=2,
                                          start_record_id=2 << 32)
        scal = list(reader.iter_rows(MemoryStream(data), file_id=2,
                                     start_record_id=2 << 32))
        assert res.to_rows() == scal
        assert res.n_rows == len(scal) > 0

    def test_columnar_path_engages(self, monkeypatch):
        reader = self._reader()
        data = generate_exp3(40, seed=10)
        called = {}
        orig = reader._hierarchical_columnar_setup

        def spy(*a, **k):
            called["yes"] = True
            ctx = orig(*a, **k)
            assert ctx is not None  # no silent scalar fallback
            return ctx

        monkeypatch.setattr(reader, "_hierarchical_columnar_setup", spy)
        res = reader.read_result_columnar(MemoryStream(data))
        assert called.get("yes")
        assert res.rows_factory is not None  # rows stay lazy
        assert res.arrow_factory is not None

    def test_scalar_fallback_variable_size_occurs(self):
        """variable_size_occurs shifts per-record offsets: the columnar
        plan cannot apply and the scalar path must serve the read."""
        params = ReaderParameters(
            is_record_sequence=True,
            variable_size_occurs=True,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_id_redefine_map={"C": "STATIC_DETAILS",
                                         "P": "CONTACTS"},
                field_parent_map={"CONTACTS": "STATIC-DETAILS"}))
        reader = VarLenReader(EXP3_COPYBOOK, params)
        data = generate_exp3(30, seed=11)
        res = reader.read_result_columnar(MemoryStream(data))
        scal = list(reader.iter_rows(MemoryStream(data)))
        assert res.to_rows() == scal

    @pytest.mark.parametrize("extra", [dict(select=("COMPANY-ID",)),
                                       dict(start_offset=2)])
    def test_scalar_fallback_for_unsupported_configs(self, extra):
        """select projection and record start offsets have no faithful
        columnar hierarchical mapping (round-3 review findings): rows
        must come from the scalar oracle in those configurations."""
        params = ReaderParameters(
            is_record_sequence=True,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_id_redefine_map={"C": "STATIC_DETAILS",
                                         "P": "CONTACTS"},
                field_parent_map={"CONTACTS": "STATIC-DETAILS"}),
            **extra)
        reader = VarLenReader(EXP3_COPYBOOK, params)
        data = generate_exp3(30, seed=12)
        if extra.get("start_offset"):
            # prepend 2 junk bytes inside each record's payload
            import numpy as np
            from cobrix_tpu import native
            offs, lens = native.rdw_scan(data, big_endian=False)
            buf = bytearray()
            for o, l in zip(offs, lens):
                payload = b"ZZ" + data[o:o + l]
                buf += bytes([0, 0, len(payload) & 0xFF,
                              len(payload) >> 8]) + payload
            data = bytes(buf)
        res = reader.read_result_columnar(MemoryStream(data))
        scal = list(reader.iter_rows(MemoryStream(data)))
        assert res.rows == scal
        assert len(scal) > 0


class TestHierarchicalArrow:
    """The span-based columnar Arrow assembly (reader/hierarchical_arrow)
    must produce exactly the table the row path produces."""

    def _read(self, **extra):
        import os
        import tempfile

        from cobrix_tpu import read_cobol
        from cobrix_tpu.testing import generators as g

        raw = g.generate_hierarchical(40, seed=13)
        seg_opts = {f"redefine_segment_id_map:{i}": f"{name} => {sid}"
                    for i, (sid, name) in enumerate(
                        g.HIERARCHICAL_SEGMENT_MAP.items())}
        child_opts = {f"segment-children:{i}": f"{parent} => {child}"
                      for i, (child, parent) in enumerate(
                          g.HIERARCHICAL_PARENT_MAP.items())}
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "hier.dat")
            with open(path, "wb") as f:
                f.write(raw)
            return read_cobol(
                path, copybook_contents=g.HIERARCHICAL_COPYBOOK,
                is_record_sequence="true", segment_field="SEGMENT-ID",
                **seg_opts, **child_opts, **extra)

    def test_arrow_factory_matches_row_built_table(self):
        from cobrix_tpu.reader.arrow_out import rows_to_table

        res = self._read(generate_record_id="true")
        tbl = res.to_arrow()
        rows_tbl = rows_to_table(res.to_rows(), res.schema)
        assert tbl.schema == rows_tbl.schema
        assert tbl.num_rows == rows_tbl.num_rows
        assert tbl.to_pylist() == rows_tbl.to_pylist()

    def test_arrow_factory_matches_rows_collapse_root(self):
        from cobrix_tpu.reader.arrow_out import rows_to_table

        res = self._read(schema_retention_policy="collapse_root")
        tbl = res.to_arrow()
        rows_tbl = rows_to_table(res.to_rows(), res.schema)
        assert tbl.schema == rows_tbl.schema
        assert tbl.to_pylist() == rows_tbl.to_pylist()

    def test_exp3_hierarchical_arrow_matches_rows(self):
        from cobrix_tpu.reader.arrow_out import rows_to_table
        from cobrix_tpu.reader.schema import CobolOutputSchema

        params = ReaderParameters(
            is_record_sequence=True,
            generate_record_id=True,
            multisegment=MultisegmentParameters(
                segment_id_field="SEGMENT-ID",
                segment_id_redefine_map={"C": "STATIC_DETAILS",
                                         "P": "CONTACTS"},
                field_parent_map={"CONTACTS": "STATIC-DETAILS"}))
        reader = VarLenReader(EXP3_COPYBOOK, params)
        data = generate_exp3(60, seed=14)
        res = reader.read_result_columnar(MemoryStream(data), file_id=1,
                                          start_record_id=1 << 32)
        schema = CobolOutputSchema(
            reader.copybook, policy=params.schema_policy,
            generate_record_id=True)
        tbl = res.to_arrow(schema)
        rows_tbl = rows_to_table(res.to_rows(), schema.schema)
        assert tbl.schema == rows_tbl.schema
        assert tbl.to_pylist() == rows_tbl.to_pylist()


@pytest.mark.jax
def test_decode_once_multiseg_jax_backend_matches_numpy():
    """The decode-once multisegment path must be backend-agnostic: the
    jax (XLA) decode of the full all-redefines plan produces the same
    Arrow table as the native/numpy kernels."""
    from cobrix_tpu.reader.schema import CobolOutputSchema

    data = generate_exp2(300, seed=21)
    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS",
                                     "P": "CONTACTS"}))
    reader = VarLenReader(EXP2_COPYBOOK, params)
    schema = CobolOutputSchema(reader.copybook, policy=params.schema_policy)
    tables = {}
    for backend in ("numpy", "jax"):
        res = reader.read_result_columnar(MemoryStream(data),
                                          backend=backend)
        tables[backend] = res.to_arrow(schema)
    assert tables["numpy"].to_pylist() == tables["jax"].to_pylist()
