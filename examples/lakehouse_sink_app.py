"""Mainframe -> lakehouse, exactly once (cobrix_tpu.sink): a live
EBCDIC feed tailed into a transactional Parquet dataset, killed
mid-commit, and recovered — the dataset ends byte-identical to a
one-shot read of the final feed, and the committed files are plain
Parquet any engine (DuckDB, Polars, Spark, pyarrow.dataset) can scan."""
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cobrix_tpu import read_cobol, read_dataset, sink_cobol, tail_cobol
from cobrix_tpu.testing.faults import (LiveAppender, SinkFaultPlan,
                                       SinkKilled)

COPYBOOK = """
        01  TXN.
            05  REGION  PIC X(2).
            05  ACCOUNT PIC 9(7) COMP.
            05  MEMO    PIC X(9).
"""


def records(n, start=0):
    return b"".join(
        ("EU" if i % 3 else "US").encode("cp037")
        + i.to_bytes(4, "big")
        + f"TXN{i % 1000000:06d}".encode("cp037")
        for i in range(start, start + n))


def main():
    work = tempfile.mkdtemp(prefix="lakehouse-sink-")
    feed = os.path.join(work, "TXN.FEED.dat")
    ckpt = os.path.join(work, "checkpoints")
    dataset = os.path.join(work, "dataset")

    # a mainframe transfer growing the feed in torn, non-record-aligned
    # chunks while we consume it
    open(feed, "wb").write(records(2000))
    appender = LiveAppender(feed, records(6000, 2000),
                            slice_sizes=(37, 11, 53), pause_s=0.001)
    appender.start()

    def tailer():
        return tail_cobol(feed, copybook_contents=COPYBOOK,
                          schema_retention_policy="collapse_root",
                          checkpoint_dir=ckpt, poll_interval_s=0.05,
                          idle_timeout_s=1.0, finalize_on_idle=True,
                          batch_max_mb=0.02)

    # run 1: the consumer dies between finalizing a data file and
    # committing its manifest record — the worst crash window
    plan = SinkFaultPlan(work, action="raise").kill("pre_commit", seq=3)
    try:
        with plan.installed():
            sink_cobol(tailer(), dataset,
                       partition_by=["REGION"],
                       target_file_mb=0.1)
    except SinkKilled:
        print("consumer killed between stage-write and manifest commit")

    # run 2: restart from the checkpoint — recovery quarantines the
    # orphaned file and the batch re-drives exactly once
    result = sink_cobol(tailer(), dataset,
                        partition_by=["REGION"],
                        target_file_mb=0.1)
    appender.join(10)
    print(f"recovery: {result.recovery}")
    print(f"committed {result.records_total} rows, "
          f"{result.batches} batches this run")

    got = read_dataset(dataset)
    want = read_cobol(feed, copybook_contents=COPYBOOK,
                      schema_retention_policy="collapse_root") \
        .to_arrow().replace_schema_metadata(None)
    # one final drain may still be pending if the appender outran the
    # idle timeout; drive once more until the watermark catches up
    while got.num_rows < want.num_rows:
        sink_cobol(tailer(), dataset, partition_by=["REGION"],
                   target_file_mb=0.1)
        got = read_dataset(dataset)
    # partitioning regroups rows inside each commit (one file per
    # REGION value), so compare as row SETS via a total sort key
    assert got.sort_by("ACCOUNT").equals(want.sort_by("ACCOUNT")), \
        "dataset != one-shot read"
    print(f"dataset row-identical to a one-shot read "
          f"({got.num_rows} rows, zero duplicates, zero gaps)")

    # the committed files are ordinary hive-partitioned Parquet:
    import pyarrow.dataset as pads

    engine_view = pads.dataset(os.path.join(dataset, "data"),
                               format="parquet", partitioning="hive")
    print("any engine sees:", engine_view.count_rows(), "rows across",
          sorted(os.listdir(os.path.join(dataset, "data"))))
    shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
