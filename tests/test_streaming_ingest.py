"""Exactly-once continuous ingestion (cobrix_tpu.streaming).

The chaos matrix of ISSUE 10: kill/restart cycles at randomized points
must leave the concatenation of delivered batches byte-identical to a
one-shot read of the final inputs (fixed and VRL, local and memory://,
pipelined catch-up on and off); checkpoint corruption self-heals off
the second slot; rotation drains the old generation exactly once;
truncation is a structured outcome; the incremental sparse index equals
a from-scratch index; and the serve follow mode delivers the same
exactly-once stream through replica failover.

The exactly-once consumer protocol under test is the documented one:
each delivered batch is appended to a durable-ish output, the ack
carries the output length as ``app_state``, and a restart truncates the
output back to the recovered ``app_state`` before consuming — so a
crash between delivery and ack re-drives batches into the exact hole
the truncation opened.
"""
import os
import random
import socket
import socketserver
import threading
import time

import pytest

pa = pytest.importorskip("pyarrow")

from cobrix_tpu import SourceTruncated, read_cobol, tail_cobol
from cobrix_tpu.obs.metrics import stream_metrics
from cobrix_tpu.reader.index import (
    IncrementalIndexer,
    sparse_index_generator,
)
from cobrix_tpu.reader.stream import MemoryStream
from cobrix_tpu.streaming import CheckpointStore, CobolStreamer
from cobrix_tpu.testing.faults import (
    LiveAppender,
    corrupt_cache_entry,
    rotate_source,
    truncate_source,
)

from util import hard_timeout

FIXED_COPYBOOK = """
        01  R.
            05  KEY    PIC 9(7) COMP.
            05  NAME   PIC X(9).
"""
FIXED_RS = 13

RDW_COPYBOOK = """
        01  R.
            05  K  PIC X(6).
"""


def fixed_records(n, start=0):
    return b"".join(
        (start + i).to_bytes(4, "big")
        + f"ROW{(start + i) % 1000000:06d}".encode("ascii")
        for i in range(n))


def rdw(payload):
    return bytes([0, 0, len(payload) % 256, len(payload) // 256]) \
        + payload


def rdw_records(n, start=0):
    return b"".join(rdw(f"K{i:05d}".encode("cp037"))
                    for i in range(start, start + n))


def bare(table):
    return table.replace_schema_metadata(None)


def one_shot(path, **opts):
    return bare(read_cobol(path, **opts).to_arrow())


class ExactlyOnceConsumer:
    """The documented ack protocol, in-process: output truncation to
    the committed app_state on every 'restart'."""

    def __init__(self):
        self.tables = []

    def run(self, make_ingestor, crash_after=None):
        """One consumer lifetime; crash_after = batches before the
        simulated crash (ingestor abandoned, NOTHING acked after the
        last explicit ack — the same recovery surface a SIGKILL
        leaves). Returns True when the feed idled out (finished)."""
        ing = make_ingestor()
        committed = int(ing.app_state or 0)
        del self.tables[committed:]
        n = 0
        finished = True
        for batch in ing.batches():
            self.tables.append(bare(batch.to_arrow()))
            batch.ack(app_state=len(self.tables))
            n += 1
            if crash_after is not None and n >= crash_after:
                finished = False
                break  # abandon: no close(), no further acks
        if finished:
            ing.close(finalize=True)
        return finished

    def table(self):
        return pa.concat_tables(self.tables)


FIXED_OPTS = {"copybook_contents": FIXED_COPYBOOK}
VRL_OPTS = {"copybook_contents": RDW_COPYBOOK,
            "is_record_sequence": "true",
            "generate_record_id": "true"}


@pytest.mark.parametrize("flavor,pipeline", [
    ("fixed", "0"), ("fixed", "2"), ("vrl", "0"), ("vrl", "2"),
])
def test_kill_restart_matrix_byte_identical(tmp_path, flavor, pipeline):
    """SIGKILL-shaped kill/restart at randomized points x fixed/VRL x
    pipelined catch-up on/off => byte-identical concatenation."""
    with hard_timeout(300, "kill/restart matrix"):
        rng = random.Random(hash((flavor, pipeline)) & 0xFFFF)
        payload = (fixed_records(4000) if flavor == "fixed"
                   else rdw_records(4000))
        opts = dict(FIXED_OPTS if flavor == "fixed" else VRL_OPTS)
        src = tmp_path / "feed.dat"
        ckpt = tmp_path / "ckpt"
        src.write_bytes(payload[:len(payload) // 3])
        appender = LiveAppender(str(src), payload[len(payload) // 3:],
                                slice_sizes=(7, 3, 29, 2, 111),
                                pause_s=0.001).start()
        consumer = ExactlyOnceConsumer()

        def make():
            return tail_cobol(
                str(src), checkpoint_dir=str(ckpt), auto_ack=False,
                poll_interval_s=0.02, idle_timeout_s=0.8,
                finalize_on_idle=True, batch_max_mb=0.004,
                pipeline_workers=pipeline, **opts)

        kills = 0
        while True:
            crash = rng.randint(1, 6) if kills < 3 else None
            if consumer.run(make, crash_after=crash) and appender.done:
                break
            kills += 1
            assert kills < 200, "kill/restart loop did not converge"
        assert kills >= 3
        appender.join(5)
        got = consumer.table()
        want = one_shot(str(src), **opts)
        assert got.equals(want), (
            f"{got.num_rows} rows delivered vs {want.num_rows} one-shot "
            f"after {kills} kill/restart cycles")


def test_memory_backend_prefix_tail(tmp_path):
    """Object-store tailing: new immutable objects under a memory://
    prefix are consumed exactly once, equal to the one-shot read."""
    fsspec = pytest.importorskip("fsspec")
    with hard_timeout(120, "memory tail"):
        fs = fsspec.filesystem("memory")
        prefix = f"/ingest-{os.getpid()}-{int(time.time() * 1000)}"
        fs.pipe_file(f"{prefix}/a.dat", fixed_records(300))
        consumer = ExactlyOnceConsumer()
        ing = tail_cobol(f"memory://{prefix}/",
                         checkpoint_dir=str(tmp_path / "ck"),
                         auto_ack=False, poll_interval_s=0.05,
                         idle_timeout_s=2.0, **FIXED_OPTS)
        it = ing.batches()
        batch = next(it)
        consumer.tables.append(bare(batch.to_arrow()))
        batch.ack(app_state=len(consumer.tables))
        fs.pipe_file(f"{prefix}/b.dat", fixed_records(200, 300))
        for batch in it:
            consumer.tables.append(bare(batch.to_arrow()))
            batch.ack(app_state=len(consumer.tables))
        ing.close()
        got = consumer.table()
        want = one_shot(f"memory://{prefix}/", **FIXED_OPTS)
        assert got.equals(want)


@pytest.mark.parametrize("mode", ["bitflip", "garbage"])
def test_checkpoint_corruption_self_heals(tmp_path, mode):
    """A corrupted checkpoint slot is quarantined + counted and
    recovery falls back to the other slot — the stream stays exactly
    once through the re-drive (ack protocol absorbs it)."""
    from cobrix_tpu.obs.metrics import default_registry

    with hard_timeout(180, "checkpoint corruption"):
        src = tmp_path / "feed.dat"
        ckpt = tmp_path / "ckpt"
        src.write_bytes(fixed_records(900))
        consumer = ExactlyOnceConsumer()

        def make():
            return tail_cobol(str(src), checkpoint_dir=str(ckpt),
                              auto_ack=False, poll_interval_s=0.02,
                              idle_timeout_s=0.4, finalize_on_idle=True,
                              batch_max_mb=0.002, **FIXED_OPTS)

        consumer.run(make, crash_after=4)  # several acked commits
        counter = default_registry().counter(
            "cobrix_cache_corruption_total", label_names=("plane",))
        before = counter.value(plane="checkpoint")
        corrupt_cache_entry(str(ckpt), "checkpoint", mode)
        while not consumer.run(make):
            pass
        assert counter.value(plane="checkpoint") == before + 1
        quarantined = os.listdir(ckpt / "quarantine")
        assert len(quarantined) >= 1
        assert consumer.table().equals(one_shot(str(src), **FIXED_OPTS))


def test_both_slots_corrupt_restarts_from_zero(tmp_path):
    with hard_timeout(120, "double corruption"):
        src = tmp_path / "feed.dat"
        ckpt = tmp_path / "ckpt"
        src.write_bytes(fixed_records(400))
        consumer = ExactlyOnceConsumer()

        def make():
            return tail_cobol(str(src), checkpoint_dir=str(ckpt),
                              auto_ack=False, poll_interval_s=0.02,
                              idle_timeout_s=0.4, finalize_on_idle=True,
                              batch_max_mb=0.002, **FIXED_OPTS)

        consumer.run(make, crash_after=3)
        for which in (0, 1):
            try:
                corrupt_cache_entry(str(ckpt), "checkpoint", "garbage",
                                    which=which)
            except FileNotFoundError:
                break
        while not consumer.run(make):
            pass
        assert consumer.table().equals(one_shot(str(src), **FIXED_OPTS))


def test_rotation_drains_old_generation_exactly_once(tmp_path):
    """Rename rotation mid-tail: every old-generation record exactly
    once (including bytes appended to the renamed file while the
    handle drains), then the new generation."""
    with hard_timeout(120, "rotation"):
        src = tmp_path / "app.log"
        src.write_bytes(fixed_records(50))
        m = stream_metrics()
        rotations_before = m["rotations"].value()
        ing = tail_cobol(str(src), checkpoint_dir=str(tmp_path / "ck"),
                         poll_interval_s=0.02, **FIXED_OPTS)
        it = ing.batches()
        first = next(it)
        rotated = rotate_source(str(src), fixed_records(30, 1000))
        # a late append to the ROTATED-AWAY file still belongs to the
        # old generation (the held descriptor reads it)
        with open(rotated, "ab") as f:
            f.write(fixed_records(10, 50))
        tables = [bare(first.to_arrow())]
        rows = first.records
        while rows < 90:
            batch = next(it)
            tables.append(bare(batch.to_arrow()))
            rows += batch.records
        ing.close()
        got = pa.concat_tables(tables)
        keys = got.column("R").to_pylist()
        old = sorted(k["KEY"] for k in keys if k["KEY"] < 1000)
        new = sorted(k["KEY"] for k in keys if k["KEY"] >= 1000)
        assert old == list(range(60))       # 50 + 10 late, exactly once
        assert new == list(range(1000, 1030))
        assert m["rotations"].value() == rotations_before + 1


def test_truncation_error_policy_is_structured(tmp_path):
    with hard_timeout(60, "truncation error"):
        src = tmp_path / "t.dat"
        src.write_bytes(fixed_records(80))
        ing = tail_cobol(str(src), checkpoint_dir=str(tmp_path / "ck"),
                         poll_interval_s=0.02, **FIXED_OPTS)
        it = ing.batches()
        next(it)
        truncate_source(str(src), 2 * FIXED_RS)
        with pytest.raises(SourceTruncated) as info:
            next(it)
        assert info.value.path == str(src)
        assert info.value.size < info.value.watermark
        ing.close()


def test_truncation_restart_policy_reingests(tmp_path):
    with hard_timeout(60, "truncation restart"):
        src = tmp_path / "t.dat"
        src.write_bytes(fixed_records(60))
        m = stream_metrics()
        before = m["truncations"].value()
        ing = tail_cobol(str(src), checkpoint_dir=str(tmp_path / "ck"),
                         truncation_policy="restart",
                         poll_interval_s=0.02, idle_timeout_s=0.5,
                         finalize_on_idle=True, **FIXED_OPTS)
        it = ing.batches()
        first = next(it)
        assert first.generation == 0
        with open(src, "wb") as f:  # in-place replacement, larger
            f.write(fixed_records(70, 5000))
        rest = list(it)
        ing.close()
        assert m["truncations"].value() == before + 1
        regen = pa.concat_tables([bare(b.to_arrow()) for b in rest])
        keys = [k["KEY"] for k in regen.column("R").to_pylist()]
        assert sorted(keys) == list(range(5000, 5070))
        assert all(b.generation == 1 for b in rest)


def test_mid_record_tail_waits_never_garbage(tmp_path):
    """Torn, non-record-aligned appends: no partial record is ever
    decoded; the stream converges to the one-shot read."""
    with hard_timeout(120, "torn appends"):
        src = tmp_path / "torn.dat"
        src.write_bytes(b"")
        payload = rdw_records(600)
        appender = LiveAppender(str(src), payload,
                                slice_sizes=(1, 5, 2, 9, 3),
                                pause_s=0.0005).start()
        consumer = ExactlyOnceConsumer()

        def make():
            return tail_cobol(str(src),
                              checkpoint_dir=str(tmp_path / "ck"),
                              auto_ack=False, poll_interval_s=0.01,
                              idle_timeout_s=0.8, finalize_on_idle=True,
                              **VRL_OPTS)

        while not (consumer.run(make) and appender.done):
            pass
        appender.join(5)
        assert consumer.table().equals(one_shot(str(src), **VRL_OPTS))


def test_permissive_corruption_matches_one_shot(tmp_path):
    """A corrupt RDW run inside a tailed file: the finalized stream's
    ledgered resync behavior equals the one-shot permissive read."""
    with hard_timeout(120, "permissive corruption"):
        good = rdw_records(200)
        corrupted = good[:1100] + b"\x00" * 4 + good[1100:]
        src = tmp_path / "c.dat"
        src.write_bytes(corrupted)
        opts = dict(VRL_OPTS, record_error_policy="drop_malformed")
        consumer = ExactlyOnceConsumer()

        def make():
            return tail_cobol(str(src),
                              checkpoint_dir=str(tmp_path / "ck"),
                              auto_ack=False, poll_interval_s=0.02,
                              idle_timeout_s=0.4, finalize_on_idle=True,
                              **opts)

        while not consumer.run(make):
            pass
        assert consumer.table().equals(one_shot(str(src), **opts))


def test_fail_fast_corruption_raises_structured(tmp_path):
    with hard_timeout(60, "fail-fast corruption"):
        good = rdw_records(50)
        src = tmp_path / "c.dat"
        src.write_bytes(good[:110] + b"\x00" * 4 + good[110:])
        ing = tail_cobol(str(src), checkpoint_dir=str(tmp_path / "ck"),
                         poll_interval_s=0.02, tail_grace_s=0.2,
                         idle_timeout_s=3.0, **VRL_OPTS)
        with pytest.raises(Exception, match="RDW"):
            for _ in ing.batches():
                pass
        ing.close()


def test_unsupported_tail_configs_refused():
    for bad in (dict(is_text="true"), dict(variable_size_occurs="true"),
                dict(record_length_field="K"),
                dict(file_start_offset="4")):
        with pytest.raises(ValueError, match="continuous ingestion"):
            tail_cobol("/nonexistent", copybook_contents=RDW_COPYBOOK,
                       **bad)


def test_incremental_index_equals_from_scratch(tmp_path):
    """IncrementalIndexer == sparse_index_generator over the same
    records, survives a state round-trip, and the finalized index lands
    in the store where a one-shot read finds it."""
    from cobrix_tpu.api import parse_options
    from cobrix_tpu.reader.var_len_reader import VarLenReader

    with hard_timeout(120, "incremental index"):
        data = rdw_records(1000)
        params, _ = parse_options(dict(
            copybook_contents=RDW_COPYBOOK, is_record_sequence="true",
            input_split_records="37"))
        reader = VarLenReader(RDW_COPYBOOK, params)
        want = sparse_index_generator(
            0, MemoryStream(data),
            record_header_parser=reader.record_header_parser(),
            records_per_index_entry=37)
        inc = IncrementalIndexer(records_per_entry=37)
        pos = 0
        mid_state = None
        while pos < len(data):
            length = data[pos + 2] + 256 * data[pos + 3]
            inc.add_record(4 + length, True)
            pos += 4 + length
            if mid_state is None and pos > len(data) // 2:
                mid_state = inc.state_dict()
                inc = IncrementalIndexer.from_state(mid_state)
        assert inc.entries(0) == want
        # end to end: tail with cache_dir, finalize, one-shot read hits
        cache = tmp_path / "cache"
        src = tmp_path / "v.dat"
        src.write_bytes(data)
        opts = dict(copybook_contents=RDW_COPYBOOK,
                    is_record_sequence="true", input_split_records="37",
                    cache_dir=str(cache))
        ing = tail_cobol(str(src), checkpoint_dir=str(tmp_path / "ck"),
                         poll_interval_s=0.02, idle_timeout_s=0.3,
                         finalize_on_idle=True, batch_max_mb=0.003,
                         **opts)
        tables = [bare(b.to_arrow()) for b in ing]
        warm = read_cobol(str(src), **opts)
        assert warm.metrics.as_dict()["io"].get("index_hits", 0) >= 1
        assert pa.concat_tables(tables).equals(bare(warm.to_arrow()))


# -- micro-batch satellite fixes ------------------------------------------


def test_stream_directory_nondivisible_not_starved(tmp_path):
    """A size-stable non-record-multiple file surfaces through the
    record-error policy instead of pending forever."""
    with hard_timeout(60, "starvation fix"):
        (tmp_path / "bad.dat").write_bytes(fixed_records(5) + b"\x01\x02")
        streamer = CobolStreamer(FIXED_COPYBOOK,
                                 record_error_policy="drop_malformed")
        batches = list(streamer.stream_directory(
            str(tmp_path), poll_interval=0.05, idle_timeout=2.0))
        assert len(batches) == 1
        assert len(batches[0]) == 5
        diags = batches[0].diagnostics
        assert diags is not None and diags.corrupt_records >= 1


def test_stream_directory_nondivisible_fail_fast_raises(tmp_path):
    with hard_timeout(60, "starvation fail-fast"):
        (tmp_path / "bad.dat").write_bytes(fixed_records(3) + b"\x01")
        streamer = CobolStreamer(FIXED_COPYBOOK)
        with pytest.raises(ValueError, match="does not divide"):
            list(streamer.stream_directory(
                str(tmp_path), poll_interval=0.05, idle_timeout=2.0))


def test_stream_chunks_carryover_parity():
    """Regression pin for the O(n^2) buffer fix: many tiny unaligned
    chunks still assemble the identical record stream."""
    with hard_timeout(60, "chunk carryover"):
        payload = fixed_records(200)
        chunks = [payload[i:i + 5] for i in range(0, len(payload), 5)]
        streamer = CobolStreamer(FIXED_COPYBOOK)
        rows = []
        for batch in streamer.stream_chunks(iter(chunks)):
            rows.extend(batch.to_rows())
        whole = CobolStreamer(FIXED_COPYBOOK)._batch(payload).to_rows()
        assert rows == whole
        with pytest.raises(ValueError, match="mid-record"):
            list(CobolStreamer(FIXED_COPYBOOK).stream_chunks(
                [payload[:FIXED_RS + 3]]))


# -- serve follow mode ----------------------------------------------------


class _CuttingProxy:
    """Forward to a server, hard-drop after N server->client bytes."""

    def __init__(self, target, cut_after):
        self.target = tuple(target)
        self.cut_after = cut_after
        proxy = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                upstream = socket.create_connection(proxy.target,
                                                    timeout=10)
                stop = threading.Event()

                def c2s():
                    try:
                        while not stop.is_set():
                            data = self.request.recv(65536)
                            if not data:
                                break
                            upstream.sendall(data)
                    except OSError:
                        pass

                t = threading.Thread(target=c2s, daemon=True)
                t.start()
                sent = 0
                try:
                    while sent < proxy.cut_after:
                        data = upstream.recv(
                            min(65536, proxy.cut_after - sent))
                        if not data:
                            break
                        self.request.sendall(data)
                        sent += len(data)
                finally:
                    stop.set()
                    try:
                        self.request.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.request.close()
                    upstream.close()

        self._srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                                    _H)
        self._srv.daemon_threads = True
        self.address = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_follow_mode_parity_and_metrics(tmp_path):
    """A follow subscription over a growing file == one-shot read of
    the final file; lag/watermark metrics move during the run; the
    trailer token carries the watermark; audit records follow=True."""
    from cobrix_tpu import prometheus_text
    from cobrix_tpu.obs.audit import read_audit_log
    from cobrix_tpu.serve import ScanServer
    from cobrix_tpu.serve.client import stream_scan

    with hard_timeout(180, "follow parity"):
        src = tmp_path / "feed.dat"
        total = 3000
        src.write_bytes(fixed_records(800))
        audit = tmp_path / "audit.log"
        srv = ScanServer(audit_log=str(audit)).start()
        try:
            appender = LiveAppender(str(src),
                                    fixed_records(total - 800, 800),
                                    slice_sizes=(401, 13, 77),
                                    pause_s=0.002).start()
            stream = stream_scan(
                srv.address, str(src), copybook_contents=FIXED_COPYBOOK,
                follow={"poll_interval_s": 0.02, "idle_timeout_s": 5.0},
                max_records=total)
            batches = list(stream)
            appender.join(10)
            got = bare(pa.Table.from_batches(batches))
            assert got.equals(one_shot(str(src),
                                       copybook_contents=FIXED_COPYBOOK))
            token = (stream.summary or {}).get("resume_token") or {}
            assert token.get("watermark"), "trailer token lacks watermark"
            assert stream.summary.get("follow") is True
            text = prometheus_text()
            assert "cobrix_serve_follow_sessions_total" in text
            assert "cobrix_stream_batches_total" in text
            # the audit append runs in the handler's finally AFTER the
            # trailer reached the client (by design — observability
            # must never delay the stream), so give it a moment
            deadline = time.monotonic() + 10
            records = []
            while not records and time.monotonic() < deadline:
                records = [r for r in read_audit_log(str(audit))
                           if r.request_id == stream.request_id]
                if not records:
                    time.sleep(0.05)
            assert records and records[0].follow is True
            assert records[0].outcome == "ok"
        finally:
            srv.stop()


def test_follow_failover_resumes_exactly_once(tmp_path):
    """A follow subscriber surviving a replica cut mid-stream receives
    the same exactly-once stream via the watermark token (PR 9 failover
    extended to live sources)."""
    from cobrix_tpu.serve import ScanServer
    from cobrix_tpu.serve.client import stream_scan

    with hard_timeout(300, "follow failover"):
        src = tmp_path / "feed.dat"
        total = 3000
        src.write_bytes(fixed_records(total))
        srv1 = ScanServer().start()
        srv2 = ScanServer().start()
        proxy = _CuttingProxy(srv1.address, cut_after=20000)
        try:
            stream = stream_scan(
                [proxy.address, srv2.address], str(src),
                replica_seed=0,
                copybook_contents=FIXED_COPYBOOK,
                follow={"poll_interval_s": 0.02, "idle_timeout_s": 5.0,
                        "batch_max_mb": 0.005},
                max_records=total)
            got = bare(pa.Table.from_batches(list(stream)))
            assert stream.failovers >= 1
            assert got.equals(one_shot(str(src),
                                       copybook_contents=FIXED_COPYBOOK))
        finally:
            proxy.stop()
            srv1.stop()
            srv2.stop()


def test_follow_admission_quota(tmp_path):
    """The (max_followers + 1)-th subscription is refused with a
    structured follower_quota rejection while the held ones stream."""
    from cobrix_tpu.serve import ScanServer, ServeError, TenantQuota
    from cobrix_tpu.serve.client import stream_scan

    with hard_timeout(120, "follower quota"):
        src = tmp_path / "feed.dat"
        src.write_bytes(fixed_records(50))
        srv = ScanServer(default_quota=TenantQuota(max_concurrent=8,
                                                   max_followers=1)
                         ).start()
        try:
            held = stream_scan(
                srv.address, str(src), copybook_contents=FIXED_COPYBOOK,
                follow={"poll_interval_s": 0.05, "idle_timeout_s": 30})
            it = iter(held)
            next(it)  # the subscription is live and holding its slot
            with pytest.raises(ServeError) as info:
                extra = stream_scan(
                    srv.address, str(src),
                    copybook_contents=FIXED_COPYBOOK, follow=True,
                    max_failovers=0)
                list(extra)
            assert "follower" in str(info.value)
            snap = srv.controller.snapshot()
            assert snap["tenants"]["default"]["followers"] == 1
            held.close()
        finally:
            srv.stop()


def test_checkpoint_store_two_slot_alternation(tmp_path):
    from cobrix_tpu.streaming import StreamCheckpoint

    store = CheckpointStore(str(tmp_path / "ck"))
    for i in range(5):
        store.commit(StreamCheckpoint(delivered_records=i))
    loaded = CheckpointStore(str(tmp_path / "ck")).load()
    assert loaded.delivered_records == 4
    slots = [p for p in store.slot_paths() if os.path.exists(p)]
    assert len(slots) == 2  # both slots populated, alternating


def test_streamcheck_sigkill_subprocess():
    """The real-SIGKILL harness (tools/streamcheck.py): consumer
    subprocesses killed by os._exit AND a parent SIGKILL mid-ingest,
    restarted from the checkpoint, byte-identical output (the tier-1
    smoke; --sweep widens it under the slow tier)."""
    import importlib.util

    with hard_timeout(300, "streamcheck"):
        spec = importlib.util.spec_from_file_location(
            "streamcheck", os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools",
                                        "streamcheck.py"))
        streamcheck = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(streamcheck)
        assert streamcheck.check_exactly_once(
            "fixed", streamcheck.make_records(1500),
            {"copybook_contents": streamcheck.COPYBOOK}, kill_cycles=2)


@pytest.mark.slow
def test_kill_restart_fuzz_sweep(tmp_path):
    """Wider randomized kill-point sweep (the slow tier of the chaos
    matrix)."""
    with hard_timeout(600, "fuzz sweep"):
        for seed in range(4):
            rng = random.Random(seed)
            payload = rdw_records(3000)
            src = tmp_path / f"feed{seed}.dat"
            ckpt = tmp_path / f"ck{seed}"
            src.write_bytes(payload[:rng.randint(50, 2000)])
            appender = LiveAppender(
                str(src), payload[len(src.read_bytes()):],
                slice_sizes=(rng.randint(1, 9), rng.randint(1, 50)),
                pause_s=0.0005).start()
            consumer = ExactlyOnceConsumer()

            def make(src=src, ckpt=ckpt):
                return tail_cobol(
                    str(src), checkpoint_dir=str(ckpt), auto_ack=False,
                    poll_interval_s=0.01, idle_timeout_s=0.6,
                    finalize_on_idle=True, batch_max_mb=0.003,
                    **VRL_OPTS)

            kills = 0
            while True:
                crash = rng.randint(1, 8) if kills < 5 else None
                if consumer.run(make, crash_after=crash) \
                        and appender.done:
                    break
                kills += 1
            appender.join(5)
            assert consumer.table().equals(one_shot(str(src),
                                                    **VRL_OPTS))
