"""Per-read remote-IO counters.

One `IoStats` rides on each read's `ObsContext` (obs.context) exactly
like the compile-cache scope: every thread working for the read — the
caller, pipeline stage threads, the var-len shard pool — sees the same
object, and forked multihost workers ship their worker-local counts
home for merging. `ReadMetrics.finalize` publishes the totals both on
`as_dict()["io"]` and into the default obs registry, so per-read
assertions and fleet-level Prometheus scrapes read the same numbers.
"""
from __future__ import annotations

import threading
from typing import Dict

# every counter the io layer emits; dict key order is reporting order
KEYS = (
    "block_hits",         # block-cache reads served from disk
    "block_misses",       # block-cache reads that went to storage
    "block_put_bytes",    # bytes written into the block cache
    "block_evictions",    # cache files removed by the LRU budget
    "block_corrupt",      # block entries failing verification (quarantined)
    "index_hits",         # sparse-index store loads (no sequential pass)
    "index_misses",       # store lookups that fell through to a scan
    "index_saves",        # freshly-computed indexes persisted
    "index_corrupt",      # index payloads failing verification (quarantined)
    "prefetch_issued",    # read-ahead fetches scheduled
    "prefetch_hits",      # consumer reads served by a finished prefetch
    "prefetch_waits",     # consumer reads that waited on an in-flight one
    "prefetch_unused",    # prefetched blocks never consumed
    "bytes_fetched",      # bytes actually pulled from the storage backend
    "bytes_from_cache",   # bytes served from the persistent block cache
    "peer_hits",          # local misses answered by a warm fleet peer
    "peer_misses",        # peer-tier attempts that fell through to backend
    "bytes_from_peer",    # bytes served out of a peer's block cache
    "compressed_bytes_in",     # wire bytes fed through the inflater
    "decompressed_bytes_out",  # bytes the inflater produced this read
    "inflate_s",               # seconds spent inside codec decompress
    "inflate_skipped",         # decompressed blocks served without inflating
    "compress_corrupt",        # compressed-plane damage (stream or index)
)

# counters carrying fractional values (everything else coerces to int on
# merge so version-skewed workers can't ship floats into exact counters)
FLOAT_KEYS = frozenset({"inflate_s"})


class IoStats:
    """Thread-safe counter bag for one read's remote-IO activity."""

    __slots__ = ("_lock", "counts", "memo")

    def __init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = dict.fromkeys(KEYS, 0)
        # per-read remote-metadata memo keyed ('size'|'fingerprint', url):
        # a backend metadata probe (fs.size/fs.ukey — a network round
        # trip each) runs once per read, not once per open/plan/validate
        # pass. Per-READ scope on purpose: the next read must re-probe so
        # a changed file still invalidates the cache planes.
        self.memo: Dict[tuple, object] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] += n

    def merge(self, counts: Dict[str, int]) -> None:
        """Fold a worker's `as_dict()` into this one (multihost shards
        count into a worker-local IoStats and ship it over the result
        pipe; unknown keys from version skew are dropped)."""
        with self._lock:
            for k, v in counts.items():
                if k in self.counts and v:
                    self.counts[k] += (float(v) if k in FLOAT_KEYS
                                       else int(v))

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)

    @property
    def is_zero(self) -> bool:
        with self._lock:
            return not any(self.counts.values())

    @property
    def prefetch_utilization(self) -> float:
        """Fraction of issued prefetches the consumer actually used
        (hit or waited on); 0.0 when none were issued."""
        with self._lock:
            issued = self.counts["prefetch_issued"]
            if not issued:
                return 0.0
            used = issued - self.counts["prefetch_unused"]
            return max(0.0, min(1.0, used / issued))


def current_io_stats() -> "IoStats | None":
    """The active read's IoStats (None outside a read). One thread-local
    lookup — safe on hot paths."""
    from ..obs.context import current

    ctx = current()
    return ctx.io_stats if ctx is not None else None
