"""Durable ingestion checkpoints: the exactly-once watermark store.

One `CheckpointStore` owns the recovery state of one continuous-ingest
stream: per-source watermarks (byte offset, record count, generation,
content fingerprint), the stream-wide delivery count, the consumer's
opaque ``app_state``, and the incremental-indexer state. The contract:

* **atomic + durable** — every commit is a temp+rename+fsync write
  (`utils.atomic.write_atomic`): a SIGKILL at ANY instant leaves either
  the previous complete checkpoint or the new one, never a torn file;
* **self-verifying, self-healing** — payloads carry a CRC-32 over their
  canonical JSON (`io.integrity.stamp_json_payload`) and TWO slots
  (``.a`` / ``.b``) alternate, so a checkpoint corrupted on disk (bit
  flip, torn tail, garbage) is quarantined, counted on
  ``cobrix_cache_corruption_total{plane="checkpoint"}``, and recovery
  falls back to the other slot's older-but-valid watermark — re-driving
  a few batches (which the ack protocol de-duplicates) instead of
  either crashing or silently trusting wrong offsets;
* **exactly-once with the consumer's help** — `commit(..., app_state=)`
  persists an opaque consumer token atomically WITH the watermark. A
  consumer that records its output position in ``app_state`` and
  truncates its output back to it on restart gets end-to-end
  exactly-once across arbitrary kill points (see the README's
  "Continuous ingestion" section for the recipe; `tools/streamcheck.py`
  is the executable proof).

The store is a directory, safe to place on the same volume as the data
or a cache dir; `tools/fsckcache.py` verifies and repairs it offline.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..io.integrity import (
    note_corruption,
    quarantine,
    stamp_json_payload,
    verify_json_payload,
)
from ..utils.atomic import write_atomic

# bump when the payload layout changes: old checkpoints are refused
# (a format change must restart the stream explicitly, never misread
# offsets)
_FORMAT = 1

CHECKPOINT_SUFFIX = ".ckpt"


@dataclass
class StreamCheckpoint:
    """One committed recovery point (the JSON payload, typed)."""

    seq: int = 0                      # monotonic commit counter
    delivered_records: int = 0        # records acked across the stream
    delivered_batches: int = 0
    # per-source watermarks (sources.SourceState.to_dict payloads),
    # keyed by source path
    sources: Dict[str, dict] = field(default_factory=dict)
    # file_id assignment order: position = file_id (stable across
    # restarts so Record_Id bases never shift)
    order: List[str] = field(default_factory=list)
    # opaque consumer state committed atomically with the watermark
    app_state: object = None
    # incremental sparse-index state per source path
    # (reader.index.IncrementalIndexer.state_dict payloads)
    indexers: Dict[str, dict] = field(default_factory=dict)
    errors_total: int = 0             # cumulative ledgered record errors
    updated_unix: float = 0.0

    def to_payload(self) -> dict:
        return {
            "format": _FORMAT,
            "seq": self.seq,
            "delivered_records": self.delivered_records,
            "delivered_batches": self.delivered_batches,
            "sources": self.sources,
            "order": self.order,
            "app_state": self.app_state,
            "indexers": self.indexers,
            "errors_total": self.errors_total,
            "updated_unix": self.updated_unix,
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "StreamCheckpoint":
        return cls(
            seq=int(payload.get("seq", 0)),
            delivered_records=int(payload.get("delivered_records", 0)),
            delivered_batches=int(payload.get("delivered_batches", 0)),
            sources=dict(payload.get("sources") or {}),
            order=[str(p) for p in (payload.get("order") or [])],
            app_state=payload.get("app_state"),
            indexers=dict(payload.get("indexers") or {}),
            errors_total=int(payload.get("errors_total", 0)),
            updated_unix=float(payload.get("updated_unix", 0.0)),
        )


class CheckpointStore:
    """Two-slot checkpoint persistence for one ingest stream.

    ``stream_id`` namespaces several streams sharing one directory.
    `load()` returns the newest VALID checkpoint (corrupt slots are
    quarantined + counted, the other slot answers); `commit()` writes
    the next checkpoint into the slot NOT holding the latest valid one,
    so a crash mid-write can never destroy the only good recovery
    point."""

    def __init__(self, checkpoint_dir: str, stream_id: str = "stream"):
        if not checkpoint_dir:
            raise ValueError("checkpoint_dir must be a directory path")
        self.root = checkpoint_dir
        self.stream_id = stream_id
        os.makedirs(self.root, exist_ok=True)
        self.quarantine_root = os.path.join(self.root, "quarantine")
        self._last_seq = -1
        self._last_slot: Optional[str] = None

    def slot_paths(self) -> List[str]:
        return [os.path.join(
            self.root, f"{self.stream_id}.{slot}{CHECKPOINT_SUFFIX}")
            for slot in ("a", "b")]

    def _read_slot(self, path: str) -> Optional[StreamCheckpoint]:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            payload = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self._corrupt(path, "undecodable JSON checkpoint")
            return None
        if not isinstance(payload, dict) \
                or payload.get("format") != _FORMAT:
            # an older/newer format is refused, loudly distinct from
            # corruption: offsets written under other layout rules must
            # not be trusted, and must not LOOK like disk damage
            return None
        if not verify_json_payload(payload):
            self._corrupt(path, "checkpoint checksum mismatch")
            return None
        try:
            return StreamCheckpoint.from_payload(payload)
        except (TypeError, ValueError):
            self._corrupt(path, "checkpoint fields failed to deserialize")
            return None

    def _corrupt(self, path: str, detail: str) -> None:
        quarantine(path, self.quarantine_root)
        note_corruption("checkpoint", path, detail)

    def load(self) -> Optional[StreamCheckpoint]:
        """The newest valid checkpoint, or None (fresh stream — or both
        slots corrupt, which restarts from zero: with the app_state ack
        protocol that is still exactly-once, just a full re-drive)."""
        best = None
        best_path = None
        for path in self.slot_paths():
            ckpt = self._read_slot(path)
            if ckpt is not None and (best is None or ckpt.seq > best.seq):
                best, best_path = ckpt, path
        if best is not None:
            self._last_seq = best.seq
            self._last_slot = best_path
        return best

    def commit(self, checkpoint: StreamCheckpoint) -> None:
        """Persist `checkpoint` durably (fsync) into the non-latest
        slot. Assigns the next seq; raises OSError on write failure —
        a checkpoint that cannot be made durable must NOT be treated as
        acked (unlike cache planes, this state is correctness, so it
        does not degrade silently)."""
        checkpoint.seq = max(self._last_seq, checkpoint.seq) + 1
        checkpoint.updated_unix = time.time()
        paths = self.slot_paths()
        target = paths[checkpoint.seq % 2]
        if target == self._last_slot:
            target = paths[(checkpoint.seq + 1) % 2]
        payload = stamp_json_payload(checkpoint.to_payload())
        write_atomic(target, json.dumps(payload), fsync=True)
        self._last_seq = checkpoint.seq
        self._last_slot = target


def checkpoint_files(root: str) -> List[str]:
    """Every checkpoint slot file under `root` (offline fsck surface)."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in sorted(names):
        if name.endswith(CHECKPOINT_SUFFIX):
            out.append(os.path.join(root, name))
    return out


def verify_checkpoint_file(path: str) -> Optional[str]:
    """None when `path` holds a structurally valid checkpoint; else a
    human-readable defect description (tools/fsckcache.py)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        return f"unreadable: {exc}"
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return "undecodable JSON"
    if not isinstance(payload, dict):
        return "payload is not an object"
    if payload.get("format") != _FORMAT:
        return None  # foreign format: not corruption
    if not verify_json_payload(payload):
        return "checksum mismatch"
    return None
